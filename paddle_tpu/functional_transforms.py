"""Functional transforms over Layers/Tensors — the TPU-native power tools.

The reference has no direct equivalent (its autograd is tape-only); these
wrap jax transforms so framework users get grad/vmap/checkpoint over the
Tensor/Layer types.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .tensor import Tensor

__all__ = ["value_and_grad", "functional_grad", "vmap", "checkpoint"]


def _unwrap(x):
    return jax.tree_util.tree_map(
        lambda t: t._value if isinstance(t, Tensor) else t, x,
        is_leaf=lambda t: isinstance(t, Tensor))


def _wrap(x):
    return jax.tree_util.tree_map(lambda a: Tensor(a), x)


def value_and_grad(fn, argnums=0, has_aux=False):
    """jax.value_and_grad over Tensor pytrees."""
    vg = jax.value_and_grad(fn, argnums=argnums, has_aux=has_aux)

    def wrapped(*args, **kwargs):
        return vg(*args, **kwargs)

    return wrapped


def functional_grad(fn, argnums=0, has_aux=False):
    return jax.grad(fn, argnums=argnums, has_aux=has_aux)


def vmap(fn, in_axes=0, out_axes=0):
    return jax.vmap(fn, in_axes=in_axes, out_axes=out_axes)


def checkpoint(fn, policy=None, prevent_cse=True):
    """ref: paddle.distributed.fleet.utils.recompute — rematerialization."""
    pol = None
    if policy == "dots_saveable":
        pol = jax.checkpoint_policies.dots_saveable
    elif policy == "nothing_saveable":
        pol = jax.checkpoint_policies.nothing_saveable
    elif policy == "dots_with_no_batch_dims_saveable":
        pol = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    return jax.checkpoint(fn, policy=pol, prevent_cse=prevent_cse)
