"""GPT — the flagship decoder-only LM.

ref parity: PaddleNLP paddlenlp/transformers/gpt/modeling.py (GPTModel,
GPTForCausalLM/GPTLMHeadModel, GPTPretrainingCriterion) and the fleet GPT-3
pretrain configs (hidden 2048 x 24 layers = 1.3B).

TPU-native design:
- attention/MLP projections are mpu Column/RowParallelLinear: dense on one
  chip, tensor-parallel (GSPMD or shard_map) under a Mesh with an 'mp' axis.
- word embedding is VocabParallelEmbedding; the LM head ties its weight via
  parallel_matmul (ref: GPTForCausalLM's shared word_embeddings).
- attention core routes through F.scaled_dot_product_attention -> Pallas
  flash attention on TPU; causal masking via is_causal (no materialised
  [S,S] mask in the hot path).
- pre-LayerNorm residual blocks (the reference GPT's normalize_before=True).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..nn import functional as F
from ..nn.initializer import Normal, ParamAttr
from ..nn.layer import Layer
from ..nn.scan_stack import (ScannedLayerStack, stack_layer_state,
                             unstack_layer_state)
from ..nn.layers_common import Dropout, Embedding, LayerList
from ..nn.layers_norm import LayerNorm
from ..tensor import Tensor
from ..distributed.fleet.mpu import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy, parallel_matmul, annotate)
from .modeling_utils import FromPretrainedMixin


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 0  # 0 -> 4*hidden
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 1024
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-5
    use_flash_attention: bool = True
    tie_word_embeddings: bool = True
    # rematerialize each decoder block in backward (ref: fleet GPT-3
    # configs train with recompute on) — ~1/3 more FLOPs for O(1)-block
    # activation memory, the enabler for large batch/seq on one chip
    recompute: bool = False
    # store the L decoder blocks as stacked [L, ...] parameters and run
    # them with ONE lax.scan: the traced/compiled HLO is O(1 block)
    # instead of O(L). TPU-native compile-time lever (the r4 campaign's
    # 1.3B attempt died in the tunnel's remote_compile RPC on the
    # unrolled 24-layer remat program); composes with recompute as the
    # standard remat-scan. Training/no-cache path only — cached decode
    # keeps the unrolled blocks (see ScannedGPTLayers.forward).
    scan_layers: bool = False
    # one [h, 3h] qkv matmul (Megatron head-interleaved layout) instead
    # of three [h, h]: fewer launches + fewer activation reads. Weight
    # layout differs from the separate projections — convert checkpoints
    # with fuse_qkv_state / split_qkv_state.
    fused_qkv: bool = False
    # interleaved ('virtual') pipeline stages for GPTForCausalLMPipe:
    # each pp rank holds v chunks and activations ride a ring ppermute,
    # shrinking the bubble to (S-1)/(m*v+S-1). ref: fleet
    # num_virtual_pipeline_stages (Megatron interleaved schedule).
    num_virtual_pipeline_stages: int = 1
    # fuse the tied LM head matmul into the loss, computed over token
    # CHUNKS of this size (lax.scan + jax.checkpoint): the [N, vocab]
    # logits tensor — 824 MB fp32 at 1.3B b4 s1024 — never exists in
    # HBM; each chunk's logits live only inside one scan step and are
    # recomputed in backward. The Liger-kernel/Megatron fused-CE idea
    # in XLA-native form. Training path only; 0 disables.
    # ref: paddlenlp parallel_cross_entropy + fused head variants.
    chunked_ce: int = 0
    # fuse the block's residual add into the following LayerNorm with
    # one Pallas pass (y=LN(x+r) and s=x+r in a single read of the
    # operands — the add->reduce boundary XLA keeps as a kernel break;
    # step anatomy r4 put the MFU gap in exactly these elementwise HBM
    # passes). A/B lever: bench.py --fused-ln. ref:
    # paddle/phi/kernels/fusion/fused_layernorm_residual_dropout_bias.
    fused_ln: bool = False
    # sequence/context parallelism for long sequences: '' (off), 'ring'
    # (KV blocks rotate by ppermute with an online-softmax accumulator;
    # arXiv:2310.01889) or 'ulysses' (all_to_all seq<->heads swap;
    # arXiv:2309.14509). Takes effect when the active mesh has an 'sp'
    # axis of size > 1; attention then runs sequence-sharded via
    # shard_map while everything pointwise in S stays GSPMD-partitioned.
    # ref: fleet sep_parallel / RingFlashAttention (meta_parallel).
    sequence_parallel: str = ""

    def __post_init__(self):
        if not self.intermediate_size:
            self.intermediate_size = 4 * self.hidden_size
        if self.sequence_parallel not in ("", "ring", "ulysses"):
            raise ValueError(
                f"sequence_parallel={self.sequence_parallel!r}: expected "
                "'', 'ring' or 'ulysses'")
        if self.sequence_parallel and self.attention_probs_dropout_prob:
            raise ValueError(
                "sequence_parallel requires attention_probs_dropout_prob"
                "=0 (the sp attention kernels carry no dropout stream; "
                "hidden_dropout_prob is fine — it is pointwise in S)")

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


# ref: PaddleNLP gpt/configuration.py pretrained init configurations +
# fleet gpt-3 1.3B yaml (hidden 2048, 24L, 16 heads, seq 2048/1024 pos 1024*2)
GPT_CONFIGS = {
    "gpt3-1.3B": dict(vocab_size=50304, hidden_size=2048,
                      num_hidden_layers=24, num_attention_heads=16,
                      max_position_embeddings=2048),
    "gpt3-345M": dict(vocab_size=50304, hidden_size=1024,
                      num_hidden_layers=24, num_attention_heads=16,
                      max_position_embeddings=1024),
    "gpt2-en": dict(vocab_size=50304, hidden_size=768,
                    num_hidden_layers=12, num_attention_heads=12,
                    max_position_embeddings=1024),
    "gpt-tiny": dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=128,
                     hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0),
}


def _init_attr(cfg):
    return ParamAttr(initializer=Normal(mean=0.0, std=cfg.initializer_range))


class GPTAttention(Layer):
    """Causal self-attention. Separate q/k/v column-parallel projections
    (head dim sharded over mp) + row-parallel output projection — the
    layout of the reference's fused_attention mp path."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.cfg = config
        h = config.hidden_size
        wa = _init_attr(config)
        if config.fused_qkv:
            # one [h, 3h] matmul instead of three [h, h]: two fewer
            # kernel launches and two fewer reads of the activation per
            # layer. Out-dim layout is the Megatron INTERLEAVE
            # [H, 3, head_dim] so an mp shard (a contiguous head range)
            # holds its own q,k,v — correct under GSPMD and shard_map
            # alike. fuse_qkv_state converts separate checkpoints.
            self.qkv_proj = ColumnParallelLinear(h, 3 * h, weight_attr=wa,
                                                 gather_output=False)
        else:
            self.q_proj = ColumnParallelLinear(h, h, weight_attr=wa,
                                               gather_output=False)
            self.k_proj = ColumnParallelLinear(h, h, weight_attr=wa,
                                               gather_output=False)
            self.v_proj = ColumnParallelLinear(h, h, weight_attr=wa,
                                               gather_output=False)
        self.out_proj = RowParallelLinear(h, h, weight_attr=wa,
                                          input_is_parallel=True)

    def _heads(self, x):
        b, s = x.shape[0], x.shape[1]
        return x.reshape([b, s, -1, self.cfg.head_dim])

    def _qkv(self, x):
        if self.cfg.fused_qkv:
            qkv = self.qkv_proj(x)               # [b, s, 3h] interleaved
            b, s = qkv.shape[0], qkv.shape[1]
            d = self.cfg.head_dim
            qkv = qkv.reshape([b, s, -1, 3, d])  # [b, s, H(local), 3, d]
            return qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]
        return (self._heads(self.q_proj(x)), self._heads(self.k_proj(x)),
                self._heads(self.v_proj(x)))

    def forward(self, x, attn_mask=None, cache=None, cache_index=None):
        q, k, v = self._qkv(x)
        from .paged_cache import PagedLayerCache, paged_layer_forward
        if isinstance(cache, PagedLayerCache):
            # serving path (nlp/serving.py): paged block cache, one
            # token per slot, per-slot positions — shared contract with
            # Llama (nlp/paged_cache.py)
            return paged_layer_forward(q, k, v, cache, self.out_proj)
        if cache_index is not None:
            # STATIC cache (jit decode fast path, nlp/generation.py):
            # fixed [B, S_max, H, D] buffers written in place at
            # cache_index — shapes never change across scan steps, so one
            # compiled program decodes every token
            return self._forward_static_cache(q, k, v, cache, cache_index)
        if cache is not None:
            # skip the concat for the zero-length initial cache: under
            # shard_map tensor parallelism k/v carry num_heads/mp LOCAL
            # heads while the pre-built empty cache has global heads
            if cache[0].shape[1]:
                from ..tensor_ops.manip import concat
                k = concat([cache[0], k], axis=1)
                v = concat([cache[1], v], axis=1)
            cache = (k, v)
        sp_out = self._maybe_sequence_parallel(q, k, v, attn_mask,
                                               cache)
        if sp_out is not None:
            return sp_out
        # causal ALWAYS applies (decoder-only LM): a user attention_mask is
        # a padding mask combined ON TOP of the causal structure (ref:
        # GPTModel builds causal&padding jointly in modeling.py's
        # _prepare_decoder_attention_mask); SDPA's tril is bottom-right
        # aligned so cached decode (sq < sk) stays correct
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.cfg.attention_probs_dropout_prob
            if self.training else 0.0,
            is_causal=True, training=self.training,
            use_flash=self.cfg.use_flash_attention)
        b, s = out.shape[0], out.shape[1]
        out = self.out_proj(out.reshape([b, s, -1]))
        return (out, cache) if cache is not None else out

    def _maybe_sequence_parallel(self, q, k, v, attn_mask, cache):
        """Route attention through ring/Ulysses sequence parallelism when
        config asks for it AND the active mesh has an 'sp' axis (>1).
        Returns the projected output, or None to fall through to SDPA.
        Training/no-cache path only: cached decode grows S dynamically,
        which a static sequence shard cannot host."""
        mode = getattr(self.cfg, "sequence_parallel", "")
        if not mode or cache is not None:
            return None
        from ..distributed.mesh import get_mesh
        mesh = get_mesh()
        if mesh is None or "sp" not in mesh.axis_names or \
                mesh.shape["sp"] <= 1:
            return None
        if attn_mask is not None:
            raise ValueError(
                "sequence_parallel attention does not take a padding "
                "attention_mask (pad to full blocks or mask the loss "
                "instead — ref: fleet sep_parallel has the same "
                "contract)")
        from ..autograd import apply_op
        from ..distributed.fleet.sequence_parallel import (
            ring_attention_spmd, ulysses_attention_spmd)
        fn = (ring_attention_spmd if mode == "ring"
              else ulysses_attention_spmd)
        out = apply_op(
            lambda qq, kk, vv: fn(qq, kk, vv, mesh, causal=True), q, k, v)
        b, s = out.shape[0], out.shape[1]
        return self.out_proj(out.reshape([b, s, -1]))

    def _forward_static_cache(self, q, k, v, cache, cache_index):
        from ..autograd import apply_op

        import math as _math

        def run(qv, kv, vv, kbuf, vbuf, idx):
            idx = jnp.asarray(idx, jnp.int32)
            zero = jnp.int32(0)
            kbuf = jax.lax.dynamic_update_slice(
                kbuf, kv.astype(kbuf.dtype), (zero, idx, zero, zero))
            vbuf = jax.lax.dynamic_update_slice(
                vbuf, vv.astype(vbuf.dtype), (zero, idx, zero, zero))
            sq, s_max = qv.shape[1], kbuf.shape[1]
            if sq == 1:
                # decode step: flash-decode kernel over the padded cache
                # (causal == "first idx+1 keys are valid" when sq == 1)
                from ..ops.attention import flash_decode
                lens = jnp.full((qv.shape[0],), idx + 1, jnp.int32)
                # a reduced-precision cache (cache_dtype='bfloat16')
                # must not break the kernel: dot_general needs matching
                # dtypes, so run the attention in the cache dtype
                out = flash_decode(qv.astype(kbuf.dtype), kbuf, vbuf,
                                   lens)
                return out.astype(qv.dtype), kbuf, vbuf
            # causal validity against absolute positions: query row r sits
            # at position idx+r and may attend keys at positions <= idx+r
            kpos = jnp.arange(s_max)[None, :]
            qpos = idx + jnp.arange(sq)[:, None]
            mask = (kpos <= qpos)[None, None]        # [1, 1, sq, S_max]
            qh, kh, vh = (jnp.swapaxes(a, 1, 2) for a in (qv, kbuf, vbuf))
            scale = 1.0 / _math.sqrt(qh.shape[-1])
            logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
            logits = jnp.where(mask, logits, -jnp.inf)
            probs = jax.nn.softmax(logits.astype(jnp.float32),
                                   axis=-1).astype(qh.dtype)
            out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
            return jnp.swapaxes(out, 1, 2), kbuf, vbuf

        idx = cache_index._value if isinstance(cache_index, Tensor) \
            else cache_index
        out, kbuf, vbuf = apply_op(run, q, k, v, cache[0], cache[1], idx)
        b, s = out.shape[0], out.shape[1]
        return self.out_proj(out.reshape([b, s, -1])), (kbuf, vbuf)


def fuse_qkv_state(state_dict, num_attention_heads):
    """Convert separate q/k/v projection leaves to the fused
    head-interleaved layout (attn.qkv_proj.*). Weight convention is
    [in, out]; fused out-dim layout is [H, 3, head_dim] flattened.
    Inverse: split_qkv_state."""
    import numpy as np
    out, groups = {}, {}
    for k, v in state_dict.items():
        for part in ("q_proj", "k_proj", "v_proj"):
            if f".{part}." in k:
                base, leaf = k.split(f".{part}.")
                groups.setdefault((base, leaf), {})[part[0]] = v
                break
        else:
            out[k] = v
    if not groups:
        hint = ""
        if any("__" in k and "q_proj" in k for k in state_dict):
            hint = (" (keys look scan_layers-stacked: unstack with "
                    "unstack_layer_state first, fuse, then re-stack)")
        raise ValueError(
            "fuse_qkv_state converted 0 q/k/v trios — no '.q_proj.' / "
            "'.k_proj.' / '.v_proj.' keys found" + hint)
    for (base, leaf), g in groups.items():
        if set(g) != {"q", "k", "v"}:
            raise ValueError(f"incomplete q/k/v trio at {base}.*.{leaf}")
        arrs = [np.asarray(g[p]._value if hasattr(g[p], "_value") else g[p])
                for p in "qkv"]
        H = num_attention_heads
        if arrs[0].ndim == 2:                       # weight [in, h]
            inn, h = arrs[0].shape
            stacked = np.stack([a.reshape(inn, H, h // H) for a in arrs],
                               axis=2)              # [in, H, 3, d]
            out[f"{base}.qkv_proj.{leaf}"] = stacked.reshape(inn, 3 * h)
        else:                                       # bias [h]
            h = arrs[0].shape[0]
            stacked = np.stack([a.reshape(H, h // H) for a in arrs],
                               axis=1)              # [H, 3, d]
            out[f"{base}.qkv_proj.{leaf}"] = stacked.reshape(3 * h)
    return out


def split_qkv_state(state_dict, num_attention_heads):
    """Inverse of fuse_qkv_state."""
    import numpy as np
    if not any(".qkv_proj." in k for k in state_dict):
        raise ValueError("split_qkv_state converted 0 fused leaves — no "
                         "'.qkv_proj.' keys found (already separate, or "
                         "scan_layers-stacked: unstack first)")
    out = {}
    for k, v in state_dict.items():
        if ".qkv_proj." not in k:
            out[k] = v
            continue
        base, leaf = k.split(".qkv_proj.")
        arr = np.asarray(v._value if hasattr(v, "_value") else v)
        H = num_attention_heads
        if arr.ndim == 2:
            inn, h3 = arr.shape
            h = h3 // 3
            sp = arr.reshape(inn, H, 3, h // H)
            parts = [sp[:, :, i].reshape(inn, h) for i in range(3)]
        else:
            h = arr.shape[0] // 3
            sp = arr.reshape(H, 3, h // H)
            parts = [sp[:, i].reshape(h) for i in range(3)]
        for name, a in zip(("q_proj", "k_proj", "v_proj"), parts):
            out[f"{base}.{name}.{leaf}"] = a
    return out


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        wa = _init_attr(config)
        self.fc1 = ColumnParallelLinear(
            config.hidden_size, config.intermediate_size, weight_attr=wa,
            gather_output=False)
        self.fc2 = RowParallelLinear(
            config.intermediate_size, config.hidden_size, weight_attr=wa,
            input_is_parallel=True)
        self.act = getattr(F, config.hidden_act)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, x):
        return self.dropout(self.fc2(self.act(self.fc1(x))))


class GPTDecoderLayer(Layer):
    """Pre-LN block (ref: gpt/modeling.py TransformerDecoderLayer with
    normalize_before=True)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.cfg = config
        eps = config.layer_norm_epsilon
        self.ln_1 = LayerNorm(config.hidden_size, epsilon=eps)
        self.attn = GPTAttention(config)
        self.dropout1 = Dropout(config.hidden_dropout_prob)
        self.ln_2 = LayerNorm(config.hidden_size, epsilon=eps)
        self.mlp = GPTMLP(config)

    def forward(self, x, attn_mask=None, cache=None, cache_index=None):
        residual = x
        h = self.ln_1(x)
        if cache is not None:
            h, cache = self.attn(h, attn_mask, cache,
                                 cache_index=cache_index)
        else:
            h = self.attn(h, attn_mask)
        h = self.dropout1(h)
        if getattr(self.cfg, "fused_ln", False):
            # one Pallas pass: s = residual + h AND ln_2(s) — saves a
            # full re-read of s between the add and the norm
            from .modeling_utils import fused_residual_ln
            y, s = fused_residual_ln(residual, h, self.ln_2)
            x = s + self.mlp(y)
        else:
            x = residual + h
            x = x + self.mlp(self.ln_2(x))
        return (x, cache) if cache is not None else x


def _recompute_block(blk, x, attention_mask):
    """jax.checkpoint around one decoder block (array-level function; layer
    params are closed-over tracers, which checkpoint treats as implicit
    inputs). Full recompute: only the block INPUT is saved — saving dot
    outputs (dots_saveable) keeps ~300MB/layer of qkv/mlp activations
    alive and defeats the point on a 16GB chip."""
    from ..autograd import in_jax_trace

    def f(xa):
        out = blk(Tensor(xa), attention_mask)
        return out._value if isinstance(out, Tensor) else out

    xa = x._value if isinstance(x, Tensor) else x
    if not in_jax_trace((xa,)):
        return blk(x, attention_mask)  # eager: nothing to rematerialize
    return Tensor(jax.checkpoint(f)(xa), stop_gradient=False)


class ScannedGPTLayers(ScannedLayerStack):
    """GPT's L decoder blocks through the generic scan-over-layers stack
    (nn/scan_stack.py — O(1-block) compiled program; the gpt3-1.3B
    remote-compile mitigation, BENCHLOG r4)."""

    def __init__(self, config: GPTConfig):
        super().__init__(
            [GPTDecoderLayer(config)
             for _ in range(config.num_hidden_layers)],
            has_dropout=(config.hidden_dropout_prob > 0
                         or config.attention_probs_dropout_prob > 0),
            recompute=config.recompute)


class GPTEmbeddings(Layer):
    """word (vocab-parallel) + learned position embeddings."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.word_embeddings = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size,
            weight_attr=_init_attr(config))
        self.position_embeddings = Embedding(
            config.max_position_embeddings, config.hidden_size,
            weight_attr=_init_attr(config))
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, position_ids=None):
        if position_ids is None:
            s = input_ids.shape[1]
            position_ids = Tensor(jnp.arange(s, dtype=jnp.int32)[None, :])
        return self.dropout(self.word_embeddings(input_ids)
                            + self.position_embeddings(position_ids))


def _resolve_config(name, **overrides):
    cfg = dict(GPT_CONFIGS[name])
    cfg.update(overrides)
    return GPTConfig(**cfg)


def _coerce_config(config, kwargs):
    if config is None:
        return GPTConfig(**kwargs)
    if isinstance(config, dict):
        return GPTConfig(**config)
    return config


class GPTModel(FromPretrainedMixin, Layer):
    """ref: paddlenlp/transformers/gpt/modeling.py GPTModel."""

    def __init__(self, config: GPTConfig = None, **kwargs):
        super().__init__()
        config = _coerce_config(config, kwargs)
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        if config.scan_layers:
            self.h = ScannedGPTLayers(config)
        else:
            self.h = LayerList([GPTDecoderLayer(config)
                                for _ in range(config.num_hidden_layers)])
        self.ln_f = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)

    @classmethod
    def from_config_name(cls, name, **overrides):
        return cls(_resolve_config(name, **overrides))


    def forward(self, input_ids, position_ids=None, attention_mask=None,
                use_cache=False, cache=None, cache_index=None):
        if position_ids is None and cache_index is not None:
            idx = cache_index._value if isinstance(cache_index, Tensor) \
                else cache_index
            idx = jnp.asarray(idx)
            s = input_ids.shape[1]
            if idx.ndim:
                # per-slot positions (paged serving decode): [B] index
                # vector -> [B, s] position grid
                position_ids = Tensor(
                    idx[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :])
            else:
                position_ids = Tensor(
                    (idx + jnp.arange(s, dtype=jnp.int32))[None, :])
        elif position_ids is None and cache is not None:
            # cached decode: positions continue after the cache length
            # (ref: GPTModel.forward's past_length offset)
            past = cache[0][0].shape[1]
            s = input_ids.shape[1]
            position_ids = Tensor(
                (past + jnp.arange(s, dtype=jnp.int32))[None, :])
        # causal structure is added by the attention op itself; the user
        # mask is padding-only (ref paddlenlp GPTModel's
        # _prepare_decoder_attention_mask)
        from .modeling_utils import normalize_attention_mask
        attention_mask = normalize_attention_mask(attention_mask)
        x = self.embeddings(input_ids, position_ids)
        x = annotate(x, "dp", None, None)
        new_caches = [] if (use_cache or cache is not None) else None
        if self.config.scan_layers:
            if new_caches is not None:
                raise NotImplementedError(
                    "scan_layers=True does not support the KV-cache "
                    "decode path (the static per-layer cache contract "
                    "rides the unrolled blocks). Build the serving "
                    "model with scan_layers=False — checkpoints convert "
                    "via unstack_layer_state().")
            x = self.h(x, attention_mask)
            return self.ln_f(x)
        for i, blk in enumerate(self.h):
            if new_caches is not None:
                layer_cache = cache[i] if cache is not None else (
                    Tensor(jnp.zeros((x.shape[0], 0,
                                      self.config.num_attention_heads,
                                      self.config.head_dim),
                                     dtype=x.dtype)),) * 2
                x, c = blk(x, attention_mask, layer_cache,
                           cache_index=cache_index)
                new_caches.append(c)
            elif self.config.recompute and self.training:
                x = _recompute_block(blk, x, attention_mask)
            else:
                x = blk(x, attention_mask)
        x = self.ln_f(x)
        if new_caches is not None:
            return x, new_caches
        return x


class GPTForCausalLM(FromPretrainedMixin, Layer):
    """GPTModel + tied vocab-parallel LM head (ref: GPTForCausalLM /
    GPTLMHeadModel in gpt/modeling.py)."""

    def __init__(self, config: GPTConfig = None, **kwargs):
        super().__init__()
        self.gpt = GPTModel(config, **kwargs)
        self.config = self.gpt.config

    @classmethod
    def from_config_name(cls, name, **overrides):
        return cls(_resolve_config(name, **overrides))


    def forward(self, input_ids, position_ids=None, attention_mask=None,
                use_cache=False, cache=None, cache_index=None):
        out = self.gpt(input_ids, position_ids, attention_mask,
                       use_cache=use_cache, cache=cache,
                       cache_index=cache_index)
        if use_cache or cache is not None:
            hidden, new_cache = out
        else:
            hidden, new_cache = out, None
        if (getattr(self.config, "chunked_ce", 0) and self.training
                and new_cache is None):
            # fused head+loss: hand the criterion the HIDDEN states and
            # the tied embedding weight — GPTPretrainingCriterion runs
            # the head matmul chunk-by-chunk inside the loss so the
            # full [N, vocab] logits never materialize (config docs).
            # Under a trace, snapshot the weight's CURRENT (traced,
            # AMP-cast) value into a fresh Tensor: functional_call
            # restores the Parameter object's _value after forward
            # returns, so passing the Parameter itself would bake the
            # stale concrete array into the jit as a constant (no grads
            # to the tied weight through the head). EAGERLY the reverse
            # holds: a fresh Tensor is a detached tape leaf that would
            # silently swallow the tied-embedding grad under
            # loss.backward() — pass the Parameter itself there
            # (ADVICE r5 #1).
            from ..autograd import in_jax_trace
            w = self.gpt.embeddings.word_embeddings.weight
            lm_w = (Tensor(w._value, stop_gradient=w.stop_gradient)
                    if in_jax_trace((w._value,)) else w)
            return {"_loss_only_aux": True,
                    "hidden": hidden,
                    "lm_weight": lm_w,
                    "chunked_ce": int(self.config.chunked_ce)}
        # vocab stays sharded under shard_map: GPTPretrainingCriterion's
        # ParallelCrossEntropy consumes vocab-LOCAL logits (Megatron-style)
        logits = parallel_matmul(
            hidden, self.gpt.embeddings.word_embeddings.weight,
            transpose_y=True, gather_output=False)
        if new_cache is not None:
            return logits, new_cache
        return logits

    # -- generation ---------------------------------------------------------
    def generate(self, input_ids, max_new_tokens=20, temperature=1.0,
                 top_k=0, top_p=1.0, repetition_penalty=1.0, num_beams=1,
                 length_penalty=1.0, eos_token_id=None, pad_token_id=0,
                 decode_strategy=None, seed=None, cache_dtype="float32"):
        """ref: paddlenlp.generation.GenerationMixin. Greedy
        (temperature=0/top_k=0) or top-k sampled decode runs the eager KV-
        cache loop below (parity surface); top_p / repetition_penalty /
        eos early-stop / beam search delegate to the jit-compiled decode
        in paddle_tpu.nlp.generation (one XLA program, the fast path)."""
        if (num_beams > 1 or top_p < 1.0 or repetition_penalty != 1.0
                or eos_token_id is not None or decode_strategy is not None
                or str(cache_dtype) != "float32"):
            from .generation import generate as _jit_generate
            return _jit_generate(
                self, input_ids, max_new_tokens=max_new_tokens,
                temperature=temperature, top_k=top_k, top_p=top_p,
                repetition_penalty=repetition_penalty, num_beams=num_beams,
                length_penalty=length_penalty, eos_token_id=eos_token_id,
                pad_token_id=pad_token_id, decode_strategy=decode_strategy,
                seed=0 if seed is None else seed, cache_dtype=cache_dtype)
        was_training = self.training
        self.eval()
        ids = input_ids if isinstance(input_ids, Tensor) else Tensor(input_ids)
        key = jax.random.PRNGKey(0 if seed is None else seed)
        logits, cache = self.forward(ids, use_cache=True)
        out_ids = ids._value
        for _ in range(max_new_tokens):
            last = logits._value[:, -1, :].astype(jnp.float32)
            if top_k and temperature > 0:
                vals, idx = jax.lax.top_k(last / temperature, top_k)
                key, sub = jax.random.split(key)
                pick = jax.random.categorical(sub, vals)
                nxt = jnp.take_along_axis(idx, pick[:, None], axis=-1)[:, 0]
            else:
                nxt = jnp.argmax(last, axis=-1)
            nxt = nxt.astype(out_ids.dtype)
            out_ids = jnp.concatenate([out_ids, nxt[:, None]], axis=1)
            pos = Tensor(jnp.full((ids.shape[0], 1), out_ids.shape[1] - 1,
                                  dtype=jnp.int32))
            logits, cache = self.forward(
                Tensor(nxt[:, None]), position_ids=pos, cache=cache)
        if was_training:
            self.train()
        return Tensor(out_ids)


GPTLMHeadModel = GPTForCausalLM


class GPTPretrainingCriterion(Layer):
    """Masked LM loss (ref: gpt/modeling.py GPTPretrainingCriterion):
    mean of token CE where loss_mask==1, vocab-parallel safe."""

    def __init__(self, config=None):
        super().__init__()
        self.ce = ParallelCrossEntropy()

    def forward(self, prediction_scores, masked_lm_labels, loss_mask=None):
        if isinstance(prediction_scores, dict) and \
                "chunked_ce" in prediction_scores:
            loss = self._chunked_head_ce(
                prediction_scores["hidden"],
                prediction_scores["lm_weight"],
                masked_lm_labels, prediction_scores["chunked_ce"])
        else:
            loss = self.ce(prediction_scores, masked_lm_labels)
        if loss_mask is not None:
            m = loss_mask if isinstance(loss_mask, Tensor) else Tensor(loss_mask)
            num = (loss * m.astype(loss.dtype)).sum()
            den = m.astype(loss.dtype).sum()
            return num / den
        return loss.mean()

    @staticmethod
    def _chunked_head_ce(hidden, weight, labels, chunk):
        """Per-token CE with the tied-head matmul fused into the loss,
        lax.scan over token chunks + jax.checkpoint: each chunk's
        [chunk, vocab] logits live only inside one scan step (and are
        recomputed in backward), so peak HBM holds chunk*vocab instead
        of B*S*vocab. Grads to hidden and weight flow through the scan
        transpose (weight cotangents accumulate across chunks)."""
        from ..autograd import apply_op
        from ..distributed.fleet.mpu import axis_bound
        if axis_bound("mp"):
            # inside shard_map the weight is the vocab-LOCAL shard: the
            # chunked lse/gather would silently cover one shard's
            # partition function. ParallelCrossEntropy owns that path.
            raise NotImplementedError(
                "chunked_ce does not run inside shard_map tensor "
                "parallelism (vocab-sharded weight) — use the default "
                "head + ParallelCrossEntropy there; under GSPMD "
                "annotation-based mp, chunked_ce is fine (XLA "
                "partitions the per-chunk matmul globally)")

        def run(h, w, y):
            b, s, hd = h.shape
            n = b * s
            h2 = h.reshape(n, hd)
            y2 = y.reshape(n)
            c = max(1, min(int(chunk), n))
            pad = (-n) % c
            if pad:
                h2 = jnp.concatenate(
                    [h2, jnp.zeros((pad, hd), h2.dtype)])
                y2 = jnp.concatenate(  # pad rows count as ignored
                    [y2, jnp.full((pad,), -100, y2.dtype)])
            hc = h2.reshape(-1, c, hd)
            yc = y2.reshape(-1, c)

            @jax.checkpoint
            def body(carry, xs):
                h_c, y_c = xs
                logits = jnp.einsum(
                    "ch,vh->cv", h_c, w,
                    preferred_element_type=jnp.float32)
                lse = jax.scipy.special.logsumexp(logits, axis=-1)
                # ignore_index=-100 parity with ParallelCrossEntropy:
                # ignored positions contribute EXACTLY 0 loss
                ok = y_c != -100
                safe = jnp.clip(y_c.astype(jnp.int32), 0, None)
                picked = jnp.take_along_axis(
                    logits, safe[:, None], axis=-1)[:, 0]
                return carry, jnp.where(ok, lse - picked, 0.0)
            _, losses = jax.lax.scan(body, 0.0, (hc, yc))
            return losses.reshape(-1)[:n].reshape(b, s)

        return apply_op(run, hidden, weight,
                        labels if isinstance(labels, Tensor)
                        else Tensor(labels))


class GPTForCausalLMPipe(Layer):
    """Pipeline-parallel GPT (ref: paddlenlp/transformers/gpt/modeling_pp.py
    GPTForCausalLMPipe — PipelineLayer of [embedding, N decoder LayerDescs,
    ln_f, tied lm-head]).

    TPU-native split of responsibilities: the decoder trunk — where the
    per-layer weights live — runs through the shard_map+ppermute pipeline
    over the 'pp' mesh axis (equal-structure stages of
    num_hidden_layers/pp blocks each); embeddings, final LN and the tied
    LM head run outside the pipelined region, partitioned by GSPMD over
    dp/mp like any other op (the reference pins them to the first/last
    stage rank instead — under one SPMD program there is no rank to pin
    to, and XLA already shards the vocab matmul over 'mp').

    Composes dp x mp x pp: batch sharded over 'dp', weights over 'mp'
    (shard_model), trunk stages over 'pp'. Dropout must be 0 inside the
    trunk (stage_fn runs without a traced rng stream).
    """

    def __init__(self, config: GPTConfig = None, mesh=None, n_micro=None,
                 **kwargs):
        super().__init__()
        from ..distributed.fleet.pipeline import PipelineLayer
        config = _coerce_config(config, kwargs)
        if config.hidden_dropout_prob or config.attention_probs_dropout_prob:
            # inside the pipelined shard_map+scan there is no traced rng
            # stream: one mask would be baked in at trace time and reused
            # for every microbatch/stage/tick — silently wrong, so refuse
            raise ValueError(
                "GPTForCausalLMPipe requires hidden_dropout_prob=0 and "
                "attention_probs_dropout_prob=0 (dropout masks cannot vary "
                "across pipeline microbatches)")
        if getattr(config, "chunked_ce", 0):
            raise NotImplementedError(
                "chunked_ce is not wired through GPTForCausalLMPipe "
                "(its head computes full logits after the pipelined "
                "trunk) — set chunked_ce=0 for pipeline parallelism, or "
                "use GPTForCausalLM")
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.pipe = PipelineLayer(
            [GPTDecoderLayer(config)
             for _ in range(config.num_hidden_layers)],
            num_virtual_pipeline_stages=
            config.num_virtual_pipeline_stages)
        self.ln_f = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        self.mesh = mesh
        self.n_micro = n_micro

    @classmethod
    def from_config_name(cls, name, mesh=None, n_micro=None, **overrides):
        return cls(_resolve_config(name, **overrides), mesh=mesh,
                   n_micro=n_micro)

    def forward(self, input_ids, position_ids=None):
        x = self.embeddings(input_ids, position_ids)
        x = annotate(x, "dp", None, None)
        x = self.pipe(x, n_micro=self.n_micro, mesh=self.mesh)
        x = self.ln_f(x)
        return parallel_matmul(
            x, self.embeddings.word_embeddings.weight,
            transpose_y=True, gather_output=False)
