"""Tokenizers (WordPiece + byte-level BPE-lite).

ref parity: PaddleNLP paddlenlp/transformers/bert/tokenizer.py
(BertTokenizer = BasicTokenizer + WordpieceTokenizer over a vocab file) and
paddlenlp/transformers/gpt/tokenizer.py (GPTTokenizer, byte-level BPE).
Pure Python host-side code — tokenization never enters the XLA program, so
there is no TPU-specific design here; the contract (encode -> dict of
input_ids/token_type_ids/attention_mask, pad/truncate, decode) matches the
reference so data pipelines port over unchanged.
"""
from __future__ import annotations

import collections
import re
import unicodedata

__all__ = ["BasicTokenizer", "WordpieceTokenizer", "BertTokenizer",
           "GPTTokenizer"]


# ---------------------------------------------------------------------------
# native fast path (csrc/pttok.cc): C++ basic-tokenize + wordpiece for
# ASCII/CJK text — the common pretraining-corpus case. Out-of-scope text
# (NFD accent stripping, unicode punctuation classes) returns -2 from the
# encoder and falls back to the Python reference implementation, so parity
# is exact by construction. ref role: paddlenlp fast_tokenizer (C++).
# ---------------------------------------------------------------------------
def _load_pttok():
    import ctypes
    import os
    import subprocess

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo = os.path.dirname(pkg)
    candidates = (os.path.join(repo, "csrc", "build", "libpttok.so"),
                  os.path.join(pkg, "lib", "libpttok.so"))
    so = next((c for c in candidates if os.path.exists(c)), None)
    if so is None:
        src_dir = os.path.join(repo, "csrc")
        if os.path.exists(os.path.join(src_dir, "pttok.cc")):
            try:
                subprocess.run(["make", "-C", src_dir], capture_output=True,
                               timeout=60, text=True)
            except Exception:
                return None
        so = candidates[0] if os.path.exists(candidates[0]) else None
    if so is None:
        return None
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return None
    lib.pttok_create.restype = ctypes.c_void_p
    lib.pttok_create.argtypes = [ctypes.c_char_p, ctypes.c_long,
                                 ctypes.POINTER(ctypes.c_int), ctypes.c_int,
                                 ctypes.c_int, ctypes.c_int]
    lib.pttok_encode.restype = ctypes.c_int
    lib.pttok_encode.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_long, ctypes.c_int,
                                 ctypes.POINTER(ctypes.c_int), ctypes.c_int]
    lib.pttok_destroy.argtypes = [ctypes.c_void_p]
    return lib


_PTTOK_LIB = None
_PTTOK_TRIED = False


def _pttok():
    global _PTTOK_LIB, _PTTOK_TRIED
    if not _PTTOK_TRIED:
        _PTTOK_TRIED = True
        _PTTOK_LIB = _load_pttok()
    return _PTTOK_LIB


def _is_punctuation(ch):
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) \
            or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp):
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF or 0xF900 <= cp <= 0xFAFF)


class BasicTokenizer:
    """ref: bert/tokenizer.py BasicTokenizer — whitespace split, lowercase,
    accent strip, punctuation split, CJK char isolation."""

    def __init__(self, do_lower_case=True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text):
        out = []
        spaced = []
        for ch in text:
            if _is_cjk(ord(ch)):
                spaced.append(f" {ch} ")
            else:
                spaced.append(ch)
        for tok in "".join(spaced).split():
            if self.do_lower_case:
                tok = tok.lower()
                tok = "".join(c for c in unicodedata.normalize("NFD", tok)
                              if unicodedata.category(c) != "Mn")
            out.extend(self._split_punc(tok))
        return out

    @staticmethod
    def _split_punc(tok):
        parts, cur = [], []
        for ch in tok:
            if _is_punctuation(ch):
                if cur:
                    parts.append("".join(cur))
                    cur = []
                parts.append(ch)
            else:
                cur.append(ch)
        if cur:
            parts.append("".join(cur))
        return parts


class WordpieceTokenizer:
    """ref: bert/tokenizer.py WordpieceTokenizer — greedy longest-match
    with '##' continuation prefix."""

    def __init__(self, vocab, unk_token="[UNK]", max_input_chars_per_word=100):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_input_chars_per_word = max_input_chars_per_word

    def tokenize(self, word):
        if len(word) > self.max_input_chars_per_word:
            return [self.unk_token]
        tokens, start = [], 0
        while start < len(word):
            end, cur = len(word), None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    cur = sub
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]
            tokens.append(cur)
            start = end
        return tokens


class BertTokenizer:
    """ref: BertTokenizer. vocab: path to one-token-per-line file, or a
    dict token->id, or an iterable of tokens."""

    SPECIALS = ("[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]")

    def __init__(self, vocab, do_lower_case=True, unk_token="[UNK]",
                 pad_token="[PAD]", cls_token="[CLS]", sep_token="[SEP]",
                 mask_token="[MASK]"):
        if isinstance(vocab, str):
            with open(vocab, encoding="utf-8") as f:
                vocab = [l.rstrip("\n") for l in f]
        if not isinstance(vocab, dict):
            vocab = {tok: i for i, tok in enumerate(vocab)}
        self.vocab = dict(vocab)
        for sp in self.SPECIALS:
            if sp not in self.vocab:
                self.vocab[sp] = len(self.vocab)
        self.ids_to_tokens = {i: t for t, i in self.vocab.items()}
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordpieceTokenizer(self.vocab, unk_token)
        self.unk_token, self.pad_token = unk_token, pad_token
        self.cls_token, self.sep_token = cls_token, sep_token
        self.mask_token = mask_token

    # -- vocab building (offline tool; the reference ships vocab files) ----
    @classmethod
    def from_corpus(cls, texts, vocab_size=8000, **kw):
        """Train a wordpiece-ish vocab: whole words by frequency, then
        suffix pieces, truncated to vocab_size."""
        basic = BasicTokenizer(kw.get("do_lower_case", True))
        counts = collections.Counter()
        for t in texts:
            counts.update(basic.tokenize(t))
        vocab = list(cls.SPECIALS)
        chars = sorted({c for w in counts for c in w})
        vocab += chars + ["##" + c for c in chars]
        seen = set(vocab)
        for w, _ in counts.most_common():
            if len(vocab) >= vocab_size:
                break
            if w not in seen:
                vocab.append(w)
                seen.add(w)
        return cls({t: i for i, t in enumerate(vocab[:vocab_size])}, **kw)

    @property
    def vocab_size(self):
        return len(self.vocab)

    def tokenize(self, text):
        out = []
        for word in self.basic.tokenize(text):
            out.extend(self.wordpiece.tokenize(word))
        return out

    # -- native fast path ---------------------------------------------------
    def _ensure_native(self):
        if getattr(self, "_native_handle", None) is not None:
            return self._native_handle
        if getattr(self, "_native_failed", False):
            return None
        lib = _pttok()
        if lib is None:
            self._native_failed = True
            return None
        # '\n'-joined tokens + parallel explicit id array (vocab ids can be
        # non-contiguous when built from a token list with duplicates)
        import ctypes
        if any("\n" in t for t in self.vocab):
            # a newline inside a token would corrupt the line-split buffer
            self._native_failed = True
            return None
        inv = sorted(self.vocab.items(), key=lambda kv: kv[1])
        buf = "\n".join(t for t, _ in inv).encode("utf-8")
        ids = (ctypes.c_int * len(inv))(*[i for _, i in inv])
        h = lib.pttok_create(buf, len(buf), ids, len(inv),
                             self.vocab[self.unk_token],
                             self.wordpiece.max_input_chars_per_word
                             if hasattr(self.wordpiece,
                                        "max_input_chars_per_word") else 100)
        if not h:
            self._native_failed = True
            return None
        self._native_lib = lib
        self._native_handle = h
        return h

    def text_to_ids(self, text):
        """Token ids for `text` (no specials) — C++ fast path for
        ASCII/CJK input, Python reference otherwise. Both produce
        identical output (tested)."""
        h = self._ensure_native()
        if h is not None:
            import ctypes
            raw = text.encode("utf-8")
            cap = max(64, 2 * len(raw) + 8)
            out = (ctypes.c_int * cap)()
            n = self._native_lib.pttok_encode(
                h, raw, len(raw), int(self.basic.do_lower_case), out, cap)
            while n == -1:  # output buffer too small (pathological input)
                cap *= 4
                out = (ctypes.c_int * cap)()
                n = self._native_lib.pttok_encode(
                    h, raw, len(raw), int(self.basic.do_lower_case), out,
                    cap)
            if n >= 0:
                return list(out[:n])
        return self.convert_tokens_to_ids(self.tokenize(text))

    def __del__(self):
        h = getattr(self, "_native_handle", None)
        if h is not None:
            try:
                self._native_lib.pttok_destroy(h)
            except Exception:
                pass

    def convert_tokens_to_ids(self, tokens):
        unk = self.vocab[self.unk_token]
        return [self.vocab.get(t, unk) for t in tokens]

    def convert_ids_to_tokens(self, ids):
        return [self.ids_to_tokens.get(int(i), self.unk_token) for i in ids]

    def encode(self, text, text_pair=None, max_length=None, padding=False,
               truncation=True):
        return self(text, text_pair, max_length=max_length, padding=padding,
                    truncation=truncation)

    def __call__(self, text, text_pair=None, max_length=None, padding=False,
                 truncation=True):
        a = self.text_to_ids(text)
        b = self.text_to_ids(text_pair) if text_pair else None
        cls_id, sep_id = self.vocab[self.cls_token], self.vocab[self.sep_token]
        if max_length and truncation:
            budget = max(max_length - (3 if b is not None else 2), 0)
            if b is not None:
                # longest-first truncation (ref truncate_sequences)
                while len(a) + len(b) > budget and (a or b):
                    (a if len(a) >= len(b) else b).pop()
            else:
                a = a[:budget]
        ids = [cls_id] + a + [sep_id]
        type_ids = [0] * len(ids)
        if b is not None:
            ids += b + [sep_id]
            type_ids += [1] * (len(b) + 1)
        mask = [1] * len(ids)
        if max_length and padding:
            pad_id = self.vocab[self.pad_token]
            pad_n = max_length - len(ids)
            ids += [pad_id] * pad_n
            type_ids += [0] * pad_n
            mask += [0] * pad_n
        return {"input_ids": ids, "token_type_ids": type_ids,
                "attention_mask": mask}

    def decode(self, ids, skip_special_tokens=True):
        toks = self.convert_ids_to_tokens(ids)
        if skip_special_tokens:
            toks = [t for t in toks if t not in self.SPECIALS]
        text = " ".join(toks).replace(" ##", "")
        return text


class GPTTokenizer:
    """Byte-level BPE (ref: gpt/tokenizer.py GPTTokenizer). Either load
    (vocab, merges) or train on a corpus with .train()."""

    def __init__(self, vocab=None, merges=None, unk_token="<|endoftext|>"):
        self.unk_token = unk_token
        self.vocab = dict(vocab) if vocab else {}
        self.merges = {tuple(m): i for i, m in enumerate(merges)} \
            if merges else {}
        if self.vocab:
            self.ids_to_tokens = {i: t for t, i in self.vocab.items()}

    @classmethod
    def train(cls, texts, vocab_size=1000, unk_token="<|endoftext|>"):
        """Classic BPE training: start from bytes, iteratively merge the
        most frequent adjacent pair."""
        words = collections.Counter()
        for t in texts:
            for w in re.findall(r"\S+\s*", t):
                words[tuple(w.encode("utf-8"))] += 1
        base = {bytes([i]).decode("latin-1"): i for i in range(256)}
        vocab = dict(base)
        vocab[unk_token] = len(vocab)
        words = {tuple(bytes([b]).decode("latin-1") for b in w): c
                 for w, c in words.items()}
        merges = []
        while len(vocab) < vocab_size:
            pairs = collections.Counter()
            for w, c in words.items():
                for i in range(len(w) - 1):
                    pairs[(w[i], w[i + 1])] += c
            if not pairs:
                break
            best = max(pairs, key=pairs.get)
            merged = best[0] + best[1]
            vocab[merged] = len(vocab)
            merges.append(best)
            new_words = {}
            for w, c in words.items():
                out, i = [], 0
                while i < len(w):
                    if i + 1 < len(w) and (w[i], w[i + 1]) == best:
                        out.append(merged)
                        i += 2
                    else:
                        out.append(w[i])
                        i += 1
                new_words[tuple(out)] = new_words.get(tuple(out), 0) + c
            words = new_words
        return cls(vocab, merges, unk_token)

    @property
    def vocab_size(self):
        return len(self.vocab)

    def _bpe(self, word):
        parts = [c for c in word]
        while len(parts) > 1:
            ranked = [(self.merges.get((parts[i], parts[i + 1]), None), i)
                      for i in range(len(parts) - 1)]
            ranked = [(r, i) for r, i in ranked if r is not None]
            if not ranked:
                break
            _, i = min(ranked)
            parts = parts[:i] + [parts[i] + parts[i + 1]] + parts[i + 2:]
        return parts

    def tokenize(self, text):
        out = []
        for w in re.findall(r"\S+\s*", text):
            latin = w.encode("utf-8").decode("latin-1")
            out.extend(self._bpe(latin))
        return out

    def encode(self, text):
        unk = self.vocab.get(self.unk_token, 0)
        return [self.vocab.get(t, unk) for t in self.tokenize(text)]

    def __call__(self, text, max_length=None, padding=False,
                 truncation=True):
        ids = self.encode(text)
        if max_length and truncation:
            ids = ids[:max_length]
        mask = [1] * len(ids)
        if max_length and padding:
            pad = self.vocab.get(self.unk_token, 0)
            mask += [0] * (max_length - len(ids))
            ids += [pad] * (max_length - len(ids))
        return {"input_ids": ids, "attention_mask": mask}

    def decode(self, ids):
        toks = [self.ids_to_tokens.get(int(i), "") for i in ids]
        return "".join(toks).encode("latin-1", errors="ignore") \
            .decode("utf-8", errors="ignore")
