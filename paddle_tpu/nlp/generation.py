"""Jit-compiled autoregressive decoding — the fast path behind
GPTForCausalLM.generate's eager loop.

ref parity: paddlenlp.generation.GenerationMixin (greedy / top-k sampling
with a KV cache). The reference dispatches one CUDA graph per step;
TPU-native design compiles the ENTIRE decode into one XLA program:

- static KV cache: fixed [B, S_max, H, D] buffers per layer written in
  place with dynamic_update_slice (gpt.py's cache_index path) — shapes
  never change, so there is exactly one compile;
- the token loop is a lax.scan whose carry is (cache, position, token,
  rng): no host round-trip between steps, decode runs at HBM speed;
- prefill (the prompt) is one batched forward that fills the cache, then
  the scan emits max_new_tokens tokens.
"""
from __future__ import annotations

import functools
import os
import threading

import jax
import jax.numpy as jnp

from ..nn.layer import functional_call
from ..tensor import Tensor

__all__ = ["generate", "build_decode_fn", "build_beam_decode_fn",
           "clear_decode_cache"]

# generate() convenience-path memo: build_decode_fn returns a fresh
# jax.jit object, and jit's executable cache is keyed on function
# identity — without this memo every generate() call re-traces AND
# re-compiles (measured on the axon TPU tunnel: ~30 s/call of remote
# compile for gpt2-124M, masking the actual ~ms-scale decode).  Stored
# ON the model instance: the decode closures reference the model, so a
# module-level WeakKeyDictionary entry would never die (weakref's
# value-refs-key caveat); an instance attribute makes model<->fn a pure
# cycle the gc collects when the model is dropped.
_MEMO_ATTR = "_paddle_tpu_decode_fn_memo"
_MEMO_MAX = 8  # compiled decode programs kept per model (LRU)


def clear_decode_cache(model):
    """Drop generate()'s memoized compiled decode programs for `model`.

    Needed only after an in-place structural mutation that keeps the
    params pytree identical (e.g. toggling config.use_flash_attention is
    already part of the key, but swapping a sublayer for one with the
    same param shapes is not) — jit cannot see such a change, so the
    memo would otherwise serve the old forward."""
    with _model_lock(model):
        if getattr(model, _MEMO_ATTR, None):
            getattr(model, _MEMO_ATTR).clear()


# Per-model RLock: generate() holds it across build+call
# (functional_call swaps tracers into the shared model while tracing,
# so concurrent tracing on ONE model is unsafe by construction — same
# property as torch.func's functional_call); _memoized_decode_fn
# re-acquires it under generate(). Calls on *independent* models run
# concurrently — a single module-global lock serialized them all. The
# tiny global lock below guards only lock-attr creation.
_LOCK_ATTR = "_paddle_tpu_decode_lock"
_lock_creation_lock = threading.Lock()


def _model_lock(model):
    lock = getattr(model, _LOCK_ATTR, None)
    if lock is None:
        with _lock_creation_lock:
            lock = getattr(model, _LOCK_ATTR, None)
            if lock is None:
                lock = threading.RLock()
                object.__setattr__(model, _LOCK_ATTR, lock)
    return lock


def _memoized_decode_fn(model, key, build):
    # lock covers the whole lookup/evict/build: concurrent generate()
    # threads on one model must neither double-pay a ~30s remote compile
    # for the same key nor race the LRU pop (build for a *different* key
    # is serialized too — compiles are rare, simplicity wins)
    with _model_lock(model):
        per_model = getattr(model, _MEMO_ATTR, None)
        if per_model is None:
            per_model = {}
            object.__setattr__(model, _MEMO_ATTR, per_model)
        # trace-time inputs invisible to the params pytree: the model's
        # flash flag and the flash_decode env gate (ops/attention.py
        # reads it while tracing) — both must key the compiled program
        key = key + (bool(getattr(model.config, "use_flash_attention",
                                  False)),
                     os.environ.get("PADDLE_TPU_FLASH_DECODE"))
        fn = per_model.get(key)
        if fn is None:
            if len(per_model) >= _MEMO_MAX:  # bounded: drop least-recent
                per_model.pop(next(iter(per_model)))
            fn = per_model[key] = build()
        else:  # refresh LRU order
            per_model[key] = per_model.pop(key)
        return fn


def _apply_repetition_penalty(logits, seen, penalty):
    """CTRL-style (ref: paddlenlp.generation repetition_penalty): seen
    tokens' logits are divided by `penalty` when positive, multiplied
    when negative — always pushing them DOWN."""
    pen = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(seen, pen, logits)


def _mask_top_p(logits, top_p):
    """Nucleus filtering (jit-safe): keep the smallest prefix of the
    descending-softmax whose cumulative probability covers top_p; the
    rest go to -inf. ref: paddlenlp TopPProcess."""
    sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_l, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep everything before the crossing point, and always the top token
    keep_sorted = jnp.concatenate(
        [jnp.ones_like(cum[:, :1], bool), (cum < top_p)[:, :-1]], axis=-1)
    # threshold value per row: smallest kept logit
    thresh = jnp.min(jnp.where(keep_sorted, sorted_l, jnp.inf), axis=-1,
                     keepdims=True)
    return jnp.where(logits >= thresh, logits, -jnp.inf)


def _alloc_cache(cfg, batch, s_max, dtype):
    # GQA models (Llama-style num_key_value_heads < heads) cache only
    # the kv heads — the whole point of grouped-query attention
    kv_heads = getattr(cfg, "num_key_value_heads", 0) \
        or cfg.num_attention_heads
    return [
        (jnp.zeros((batch, s_max, kv_heads, cfg.head_dim),
                   dtype=dtype),) * 2
        for _ in range(cfg.num_hidden_layers)]


def _logits(out):
    x = out[0] if isinstance(out, tuple) else out
    return x._value if isinstance(x, Tensor) else x


def _cache_fwd(model, params, buffers, tok, cache, idx):
    """One cached forward: the Tensor-wrap/unwrap adapter for the
    cache/cache_index contract, shared by the sampling and beam paths."""
    out = functional_call(
        model, params, buffers, Tensor(tok), cache=[
            (Tensor(k), Tensor(v)) for k, v in cache],
        cache_index=idx)
    logits_t, new_cache = out
    new_cache = [(k._value if isinstance(k, Tensor) else k,
                  v._value if isinstance(v, Tensor) else v)
                 for k, v in new_cache]
    return _logits(logits_t), new_cache


def _seen_from_prompt(ids, vocab_size, pad_token_id=None):
    """[B, V] bool presence mask — scatter, not a [B, S0, V] one-hot
    (which would be ~400MB transient at GPT-3 vocab/prompt sizes).

    Prompt occurrences of pad_token_id are excluded: left-padded prompts
    (often pad==eos in GPT configs) must not leave the pad/eos logit
    permanently repetition-penalized, which would bias against
    termination. Limitation: without an attention mask we cannot tell a
    genuine prompt token that happens to equal pad_token_id from
    padding, so those are exempt too; tokens EMITTED during decode are
    penalized regardless of id (the scan update masks on `done`, not on
    token identity)."""
    b = ids.shape[0]
    seen = jnp.zeros((b, vocab_size), jnp.bool_).at[
        jnp.arange(b)[:, None], ids].set(True)
    if pad_token_id is not None:
        seen = seen.at[:, pad_token_id].set(False)
    return seen


def build_decode_fn(model, max_new_tokens, temperature=1.0, top_k=0,
                    top_p=1.0, repetition_penalty=1.0, eos_token_id=None,
                    pad_token_id=0, do_sample=None,
                    cache_dtype="float32"):
    """Compile (params, buffers, ids, rng) -> [B, S0+max_new_tokens] ids.
    model must be a GPTForCausalLM (or any model supporting the
    cache/cache_index contract).

    ref parity: paddlenlp.generation.GenerationMixin sampling path —
    temperature / top_k / top_p (nucleus) / repetition_penalty /
    eos early-stop (finished rows emit pad_token_id; shapes stay static,
    so early stop costs nothing in compiles). do_sample=True forces
    multinomial sampling even with default top_k/top_p (pure temperature
    sampling); default None infers from the filters."""
    cfg = model.config
    if do_sample is None:
        do_sample = bool(temperature > 0 and (top_k or top_p < 1.0))
    sampling = do_sample and temperature > 0
    cache_dt = jnp.dtype(str(cache_dtype))

    def decode(params, buffers, ids, rng):
        from ..autograd import no_grad
        with no_grad():
            return _decode_impl(params, buffers, ids, rng)

    def _decode_impl(params, buffers, ids, rng):
        b, s0 = ids.shape
        s_max = s0 + max_new_tokens
        cache = _alloc_cache(cfg, b, s_max, cache_dt)

        def fwd(tok, cache, idx):
            return _cache_fwd(model, params, buffers, tok, cache, idx)

        # prefill the prompt in one shot
        logits, cache = fwd(ids, cache, 0)
        last = logits[:, -1, :].astype(jnp.float32)
        track_seen = repetition_penalty != 1.0
        seen = _seen_from_prompt(ids, cfg.vocab_size, pad_token_id) \
            if track_seen else None

        def sample(last, key, seen):
            if track_seen:
                last = _apply_repetition_penalty(last, seen,
                                                 repetition_penalty)
            if not sampling:
                return jnp.argmax(last, axis=-1)
            last = last / temperature
            if top_k:
                vals, cand = jax.lax.top_k(last, top_k)
                if top_p < 1.0:
                    vals = _mask_top_p(vals, top_p)
                pick = jax.random.categorical(key, vals)
                return jnp.take_along_axis(
                    cand, pick[:, None], axis=-1)[:, 0]
            if top_p < 1.0:
                last = _mask_top_p(last, top_p)
            return jax.random.categorical(key, last)

        def step(carry, _):
            cache, idx, last, key, done, seen = carry
            key, sub = jax.random.split(key)
            nxt = sample(last, sub, seen).astype(ids.dtype)
            if eos_token_id is not None:
                nxt = jnp.where(done, jnp.asarray(pad_token_id, ids.dtype),
                                nxt)
                done = done | (nxt == eos_token_id)
            if track_seen:
                # only live rows mark their emission: finished rows emit
                # pad filler which must not accrue repetition penalty
                # (a genuinely emitted token equal to pad_token_id on a
                # live row IS still penalized)
                seen = seen | (jax.nn.one_hot(nxt, cfg.vocab_size,
                                              dtype=jnp.bool_)
                               & ~done[:, None])
            logits, cache = fwd(nxt[:, None], cache, idx)
            return (cache, idx + 1, logits[:, -1, :].astype(jnp.float32),
                    key, done, seen), nxt

        done0 = jnp.zeros((b,), jnp.bool_)
        (_, _, _, _, _, _), toks = jax.lax.scan(
            step, (cache, jnp.int32(s0), last, rng, done0, seen),
            None, length=max_new_tokens)
        return jnp.concatenate([ids, toks.T], axis=1)

    return jax.jit(decode)


def build_beam_decode_fn(model, max_new_tokens, num_beams,
                         length_penalty=1.0, eos_token_id=None,
                         pad_token_id=0, temperature=1.0,
                         repetition_penalty=1.0, cache_dtype="float32"):
    """Beam search, one XLA program (ref: paddlenlp GenerationMixin
    decode_strategy='beam_search').

    TPU-native shape: all `B*K` beams run as one batch; each scan step
    scores [B, K*V] continuations, keeps the top K, and REORDERS the KV
    cache with a batched gather over the beam axis (the reference reorders
    per-layer cache tensors with index_select — same op, but here it
    stays inside the compiled program, so the cache never round-trips to
    host). Finished beams (emitted eos) are frozen: they may only extend
    with pad at unchanged score. Final selection = best
    score / len**length_penalty per batch row. num_beams=1 degenerates to
    greedy. temperature scales logits before scoring; repetition_penalty
    follows each beam's own emitted tokens (seen masks reorder with the
    beams).
    """
    cfg = model.config
    cache_dt = jnp.dtype(str(cache_dtype))
    k = int(num_beams)
    track_seen = repetition_penalty != 1.0

    def decode(params, buffers, ids):
        from ..autograd import no_grad
        with no_grad():
            return _impl(params, buffers, ids)

    def _impl(params, buffers, ids):
        b, s0 = ids.shape
        v = cfg.vocab_size
        s_max = s0 + max_new_tokens

        def fwd(tok, cache, idx):
            return _cache_fwd(model, params, buffers, tok, cache, idx)

        # prefill the [B] prompts ONCE, then tile the cache/logits per
        # beam — k identical prompt forwards would be pure waste
        cache = _alloc_cache(cfg, b, s_max, cache_dt)
        logits, cache = fwd(ids, cache, 0)
        cache = jax.tree_util.tree_map(
            lambda a: jnp.repeat(a, k, axis=0), cache)
        last = jnp.repeat(logits[:, -1, :].astype(jnp.float32), k,
                          axis=0)                      # [B*K, V]
        seen0 = (jnp.repeat(_seen_from_prompt(ids, v, pad_token_id), k,
                            axis=0).reshape(b, k, v)
                 if track_seen else None)

        scores0 = jnp.tile(
            jnp.asarray([0.0] + [-jnp.inf] * (k - 1), jnp.float32), (b, 1))
        seq0 = jnp.full((b, k, max_new_tokens), pad_token_id, ids.dtype)
        done0 = jnp.zeros((b, k), jnp.bool_)

        def reorder(tree, beam_idx):
            """Gather beam rows: leaf [B*K, ...] -> pick beam_idx per b."""
            def one(a):
                ak = a.reshape((b, k) + a.shape[1:])
                return jnp.take_along_axis(
                    ak, beam_idx.reshape((b, k) + (1,) * (a.ndim - 1)),
                    axis=1).reshape(a.shape)
            return jax.tree_util.tree_map(one, tree)

        def step(carry, t):
            cache, idx, last, scores, seqs, done, seen = carry
            if track_seen:
                last = _apply_repetition_penalty(
                    last, seen.reshape(b * k, v), repetition_penalty)
            if temperature not in (0.0, 1.0):
                last = last / temperature
            logp = jax.nn.log_softmax(last, axis=-1).reshape(b, k, v)
            if eos_token_id is not None:
                # frozen beams: only pad continues, at zero added score
                frozen = jnp.full((v,), -jnp.inf).at[pad_token_id].set(0.0)
                logp = jnp.where(done[:, :, None], frozen[None, None, :],
                                 logp)
            total = scores[:, :, None] + logp          # [B, K, V]
            top_val, top_idx = jax.lax.top_k(total.reshape(b, k * v), k)
            beam_idx = top_idx // v                    # [B, K]
            tok = (top_idx % v).astype(ids.dtype)      # [B, K]
            # reorder everything that is per-beam state
            cache = reorder(cache, beam_idx)
            seqs = jnp.take_along_axis(seqs, beam_idx[:, :, None], axis=1)
            done = jnp.take_along_axis(done, beam_idx, axis=1)
            seqs = jax.lax.dynamic_update_slice_in_dim(
                seqs, tok[:, :, None], t, axis=2)
            if eos_token_id is not None:
                done = done | (tok == eos_token_id)
            if track_seen:
                seen = jnp.take_along_axis(seen, beam_idx[:, :, None],
                                           axis=1)
                # frozen beams continue with pad filler — mask them out
                # of the seen update so pad/eos never accrues penalty
                seen = seen | (jax.nn.one_hot(tok, v, dtype=jnp.bool_)
                               & ~done[:, :, None])
            logits, cache = fwd(tok.reshape(b * k, 1), cache, idx)
            return (cache, idx + 1, logits[:, -1, :].astype(jnp.float32),
                    top_val, seqs, done, seen), None

        (cache, _, _, scores, seqs, done, _), _ = jax.lax.scan(
            step, (cache, jnp.int32(s0), last, scores0, seq0, done0, seen0),
            jnp.arange(max_new_tokens))
        # sequence lengths: position of eos + 1, else max_new_tokens
        if eos_token_id is not None:
            is_eos = seqs == eos_token_id
            has = is_eos.any(axis=-1)
            first = jnp.argmax(is_eos, axis=-1) + 1
            lens = jnp.where(has, first, max_new_tokens)
        else:
            lens = jnp.full((b, k), max_new_tokens)
        norm = scores / (lens.astype(jnp.float32) ** length_penalty)
        best = jnp.argmax(norm, axis=-1)               # [B]
        best_seq = jnp.take_along_axis(
            seqs, best[:, None, None], axis=1)[:, 0]   # [B, T]
        return jnp.concatenate([ids, best_seq], axis=1)

    return jax.jit(decode)


def generate(model, input_ids, max_new_tokens=20, temperature=1.0,
             top_k=0, top_p=1.0, repetition_penalty=1.0, num_beams=1,
             length_penalty=1.0, eos_token_id=None, pad_token_id=0,
             decode_strategy=None, seed=0, cache_dtype="float32"):
    """One-call jitted decode. Compiled decode programs are memoized on
    the model (LRU of 8 keyed by the generation args + flash flag), so
    repeated generate() calls reuse the compiled program; only new
    (B, S0) shapes retrace. Caveat: after an in-place model mutation
    that keeps the params pytree identical (e.g. swapping a sublayer
    with same-shape params), call clear_decode_cache(model).
    decode_strategy: None (infer from args) | 'greedy_search' |
    'sampling' | 'beam_search' — ref: paddlenlp GenerationMixin.

    Thread-safe: the whole call is serialized under a per-model lock
    (tracing swaps state into the shared model; calls on independent
    models proceed concurrently). For lock-free repeated calls, build a
    fn once with build_decode_fn and manage params yourself."""
    with _model_lock(model):
        return _generate_locked(
            model, input_ids, max_new_tokens, temperature, top_k, top_p,
            repetition_penalty, num_beams, length_penalty, eos_token_id,
            pad_token_id, decode_strategy, seed, cache_dtype)


def _generate_locked(model, input_ids, max_new_tokens, temperature,
                     top_k, top_p, repetition_penalty, num_beams,
                     length_penalty, eos_token_id, pad_token_id,
                     decode_strategy, seed, cache_dtype):
    # plain-python coercion: these land in the (hashable) memo key, and
    # numpy/jax 0-d scalars were accepted here before memoization
    max_new_tokens = int(max_new_tokens)
    temperature = float(temperature)
    top_k = int(top_k)
    top_p = float(top_p)
    repetition_penalty = float(repetition_penalty)
    num_beams = int(num_beams)
    length_penalty = float(length_penalty)
    eos_token_id = None if eos_token_id is None else int(eos_token_id)
    pad_token_id = None if pad_token_id is None else int(pad_token_id)
    was_training = model.training
    model.eval()
    try:
        params, buffers = model.raw_state()
        ids = input_ids._value if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        if decode_strategy not in (None, "greedy_search", "sampling",
                                   "beam_search"):
            raise ValueError(f"unknown decode_strategy {decode_strategy!r}")
        if decode_strategy == "beam_search" or (decode_strategy is None
                                                and num_beams > 1):
            if top_k or top_p < 1.0:
                raise ValueError(
                    "beam_search scores exhaustively — top_k/top_p do not "
                    "apply (use decode_strategy='sampling' for filtered "
                    "sampling)")
            fn = _memoized_decode_fn(
                model,
                ("beam", max_new_tokens, max(num_beams, 1), length_penalty,
                 eos_token_id, pad_token_id, temperature,
                 repetition_penalty, str(cache_dtype)),
                lambda: build_beam_decode_fn(
                    model, max_new_tokens, max(num_beams, 1),
                    length_penalty, eos_token_id, pad_token_id, temperature,
                    repetition_penalty, cache_dtype=cache_dtype))
            out = fn(params, buffers, ids)
        else:
            do_sample = None
            if decode_strategy == "greedy_search":
                temperature, do_sample = 0.0, False
            elif decode_strategy == "sampling":
                do_sample = True
            fn = _memoized_decode_fn(
                model,
                ("sample", max_new_tokens, temperature, top_k, top_p,
                 repetition_penalty, eos_token_id, pad_token_id, do_sample,
                 str(cache_dtype)),
                lambda: build_decode_fn(
                    model, max_new_tokens, temperature, top_k, top_p,
                    repetition_penalty, eos_token_id, pad_token_id,
                    do_sample=do_sample, cache_dtype=cache_dtype))
            out = fn(params, buffers, ids, jax.random.PRNGKey(seed))
    finally:
        if was_training:
            model.train()
    return Tensor(out)
