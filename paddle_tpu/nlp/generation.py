"""Jit-compiled autoregressive decoding — the fast path behind
GPTForCausalLM.generate's eager loop.

ref parity: paddlenlp.generation.GenerationMixin (greedy / top-k sampling
with a KV cache). The reference dispatches one CUDA graph per step;
TPU-native design compiles the ENTIRE decode into one XLA program:

- static KV cache: fixed [B, S_max, H, D] buffers per layer written in
  place with dynamic_update_slice (gpt.py's cache_index path) — shapes
  never change, so there is exactly one compile;
- the token loop is a lax.scan whose carry is (cache, position, token,
  rng): no host round-trip between steps, decode runs at HBM speed;
- prefill (the prompt) is one batched forward that fills the cache, then
  the scan emits max_new_tokens tokens.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..nn.layer import functional_call
from ..tensor import Tensor

__all__ = ["generate", "build_decode_fn"]


def _alloc_cache(cfg, batch, s_max, dtype):
    return [
        (jnp.zeros((batch, s_max, cfg.num_attention_heads, cfg.head_dim),
                   dtype=dtype),) * 2
        for _ in range(cfg.num_hidden_layers)]


def _logits(out):
    x = out[0] if isinstance(out, tuple) else out
    return x._value if isinstance(x, Tensor) else x


def build_decode_fn(model, max_new_tokens, temperature=1.0, top_k=0):
    """Compile (params, buffers, ids, rng) -> [B, S0+max_new_tokens] ids.
    model must be a GPTForCausalLM (or any model supporting the
    cache/cache_index contract)."""
    cfg = model.config

    def decode(params, buffers, ids, rng):
        from ..autograd import no_grad
        with no_grad():
            return _decode_impl(params, buffers, ids, rng)

    def _decode_impl(params, buffers, ids, rng):
        b, s0 = ids.shape
        s_max = s0 + max_new_tokens
        cache = _alloc_cache(cfg, b, s_max, jnp.float32)

        def fwd(tok, cache, idx):
            out = functional_call(
                model, params, buffers, Tensor(tok), cache=[
                    (Tensor(k), Tensor(v)) for k, v in cache],
                cache_index=idx)
            logits_t, new_cache = out
            new_cache = [(k._value if isinstance(k, Tensor) else k,
                          v._value if isinstance(v, Tensor) else v)
                         for k, v in new_cache]
            return _logits(logits_t), new_cache

        # prefill the prompt in one shot
        logits, cache = fwd(ids, cache, 0)
        last = logits[:, -1, :].astype(jnp.float32)

        def sample(last, key):
            if temperature > 0 and top_k:
                vals, cand = jax.lax.top_k(last / temperature, top_k)
                pick = jax.random.categorical(key, vals)
                return jnp.take_along_axis(
                    cand, pick[:, None], axis=-1)[:, 0]
            return jnp.argmax(last, axis=-1)

        def step(carry, _):
            cache, idx, last, key = carry
            key, sub = jax.random.split(key)
            nxt = sample(last, sub).astype(ids.dtype)
            logits, cache = fwd(nxt[:, None], cache, idx)
            return (cache, idx + 1, logits[:, -1, :].astype(jnp.float32),
                    key), nxt

        (_, _, last_l, _), toks = jax.lax.scan(
            step, (cache, jnp.int32(s0), last, rng),
            None, length=max_new_tokens)
        return jnp.concatenate([ids, toks.T], axis=1)

    return jax.jit(decode)


def generate(model, input_ids, max_new_tokens=20, temperature=1.0,
             top_k=0, seed=0):
    """One-call jitted decode (compiles once per (B, S0, max_new_tokens)
    shape; reuse via build_decode_fn for repeated calls)."""
    was_training = model.training
    model.eval()
    try:
        params, buffers = model.raw_state()
        ids = input_ids._value if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        fn = build_decode_fn(model, max_new_tokens, temperature, top_k)
        out = fn(params, buffers, ids, jax.random.PRNGKey(seed))
    finally:
        if was_training:
            model.train()
    return Tensor(out)
