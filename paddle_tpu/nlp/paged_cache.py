"""Paged (block) KV cache — the serving-time cache contract shared by
GPT and Llama (ref: vLLM PagedAttention, arXiv:2309.06180; upstream
Paddle ships the CUDA equivalent under paddle/fluid/operators/fused/ +
FastDeploy's block-wise attention).

TPU-native shape of the idea: all shapes are STATIC so the whole decode
loop stays one compiled XLA program —

- the cache is a fixed pool of pages per layer, laid out HEAD-MAJOR
  `[Hkv, P, page_size, D]` (the layout the Pallas paged flash-decode
  kernel reads pages from HBM in, one (head, page) block per grid step);
- a `[num_slots, max_pages]` int32 page table maps each serving slot's
  token positions to pages; rows are rewritten host-side at step
  boundaries only (admission/eviction — nlp/serving.py owns the free
  list), so no recompile ever;
- page 0 is RESERVED as the trash page: inactive slots point every
  table entry at it and write position 0, so masked lanes of the
  batched step have a legal destination without any dynamic shapes;
- writes go through one `scatter` (`.at[].set`) per step; per-slot
  validity is carried by `positions` ([num_slots] int32 = tokens
  already cached) and attention masks keys at index >= positions+1.

Cache dtypes: float32 / bfloat16 store K/V directly; int8 stores
per-token-per-head symmetric-quantized rows with an f32 scale sidecar
`[Hkv, P, page_size, 1]` (the trailing singleton keeps the Mosaic lane
dim equal to the array dim, so the kernel can read scales as a legal
block — see ops/pallas/flash_decode.py).

The model integration point is `PagedLayerCache`: attention layers that
receive one as their layer cache route through
`paged_update_and_attend` instead of the dense static-cache path. It is
NOT a pytree — nlp/serving.py constructs it inside its jitted programs
from raw array arguments and unpacks the returned arrays, so it never
crosses a jit boundary.

Rewind contract (speculative decoding, round 20): rows past a slot's
committed length (`seq_lens`) are garbage by definition — attention
masks keys at index >= positions+1, and any later write at those
positions overwrites in place. So rejecting speculative KV writes
needs NO device-side cleanup: the host simply declines to advance
`seq_lens` past the accepted count (the same contract that makes the
prefix cache's private-tail pages safe to re-prefill after failover).
A spec verify dispatch writes K+1 rows per slot into already-owned
pages; committing j of them is one host-side integer add.
"""
from __future__ import annotations

import hashlib
import math

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["PagedLayerCache", "PrefixIndex", "alloc_pages",
           "prefix_fingerprints", "quantize_rows",
           "write_token_kv", "write_prompt_kv", "paged_attention_ref",
           "paged_update_and_attend", "paged_layer_forward",
           "TRASH_PAGE"]

# page index 0 is never allocated to a sequence: it is the write sink
# for masked (inactive/finished) slots and for prefill bucket tail
# pages beyond a request's allocation
TRASH_PAGE = 0

_INT8_MAX = 127.0


class PagedLayerCache:
    """One layer's view of the paged cache plus the shared routing
    state. Plain object (deliberately not a pytree — see module doc);
    `use_flash` is trace-time-static kernel routing, everything else is
    a traced array."""

    __slots__ = ("k_pages", "v_pages", "k_scale", "v_scale",
                 "page_table", "positions", "use_flash")

    def __init__(self, k_pages, v_pages, page_table, positions,
                 k_scale=None, v_scale=None, use_flash=False):
        self.k_pages = k_pages          # [Hkv, P, ps, D]
        self.v_pages = v_pages          # [Hkv, P, ps, D]
        self.k_scale = k_scale          # [Hkv, P, ps, 1] f32 | None
        self.v_scale = v_scale          # [Hkv, P, ps, 1] f32 | None
        self.page_table = page_table    # [B, MP] int32
        self.positions = positions      # [B] int32 tokens already cached
        self.use_flash = bool(use_flash)

    def replaced(self, k_pages, v_pages, k_scale=None, v_scale=None):
        """New view with updated page arrays (same table/positions/
        routing) — what an attention layer returns as its new cache."""
        return PagedLayerCache(k_pages, v_pages, self.page_table,
                               self.positions, k_scale=k_scale,
                               v_scale=v_scale, use_flash=self.use_flash)

    @property
    def page_size(self):
        return self.k_pages.shape[2]

    @property
    def quantized(self):
        return self.k_scale is not None


def alloc_pages(num_pages, page_size, kv_heads, head_dim, cache_dtype):
    """Fresh page pool for ONE layer. cache_dtype: 'float32' |
    'bfloat16' | 'int8' (int8 adds the f32 scale sidecars)."""
    dt = jnp.dtype(cache_dtype) if cache_dtype != "int8" else jnp.int8
    shape = (kv_heads, num_pages, page_size, head_dim)
    k = jnp.zeros(shape, dt)
    v = jnp.zeros(shape, dt)
    if cache_dtype == "int8":
        # two distinct arrays: the engine donates the whole pool, and
        # aliased buffers trip XLA's double-donation check
        return (k, v, jnp.zeros(shape[:3] + (1,), jnp.float32),
                jnp.zeros(shape[:3] + (1,), jnp.float32))
    return k, v, None, None


def quantize_rows(x):
    """Symmetric per-row int8 quantization over the trailing (D) axis.
    x [..., D] f32/bf16 -> (q int8 [..., D], scale f32 [..., 1])."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = amax / _INT8_MAX
    safe = jnp.where(scale == 0.0, 1.0, scale)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / safe),
                 -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    return q, scale


def _dequant(pages, scale, dtype):
    x = pages.astype(jnp.float32)
    if scale is not None:
        x = x * scale
    return x.astype(dtype)


def write_token_kv(cache: PagedLayerCache, k_new, v_new, live):
    """Write one token per slot into the pages. k_new/v_new
    [B, Hkv, D] (post-RoPE for Llama); live [B] bool — masked slots are
    redirected to the trash page so the scatter stays full-width.
    Returns the updated (k_pages, v_pages, k_scale, v_scale)."""
    ps = cache.page_size
    pos = cache.positions
    page = jnp.take_along_axis(cache.page_table,
                               (pos // ps)[:, None], axis=1)[:, 0]
    page = jnp.where(live, page, TRASH_PAGE)
    row = jnp.where(live, pos % ps, 0)
    kt = jnp.swapaxes(k_new, 0, 1)      # [Hkv, B, D]
    vt = jnp.swapaxes(v_new, 0, 1)
    if cache.quantized:
        kq, ks = quantize_rows(kt)
        vq, vs = quantize_rows(vt)
        return (cache.k_pages.at[:, page, row].set(kq),
                cache.v_pages.at[:, page, row].set(vq),
                cache.k_scale.at[:, page, row].set(ks),
                cache.v_scale.at[:, page, row].set(vs))
    return (cache.k_pages.at[:, page, row].set(kt.astype(
                cache.k_pages.dtype)),
            cache.v_pages.at[:, page, row].set(vt.astype(
                cache.v_pages.dtype)),
            None, None)


def write_prompt_kv(k_pages, v_pages, k_scale, v_scale, k_full, v_full,
                    pages_vec):
    """Prefill write: one request's whole (bucket-padded) prompt K/V
    into its pages. k_full/v_full [1, S_b, Hkv, D] with S_b a multiple
    of page_size; pages_vec [S_b // ps] int32 page ids (tail entries
    beyond the request's allocation point at TRASH_PAGE). Rows past the
    true prompt length carry garbage — they are either overwritten by
    the decode steps that reach those positions or masked by the
    attention length, never read."""
    ps = k_pages.shape[2]
    nb = k_full.shape[1] // ps

    def blocks(x):                      # [1, S_b, Hkv, D] -> [Hkv, nb, ps, D]
        x = jnp.swapaxes(x[0], 0, 1)    # [Hkv, S_b, D]
        return x.reshape(x.shape[0], nb, ps, x.shape[-1])

    kb, vb = blocks(k_full), blocks(v_full)
    if k_scale is not None:
        kq, ks = quantize_rows(kb)
        vq, vs = quantize_rows(vb)
        return (k_pages.at[:, pages_vec].set(kq),
                v_pages.at[:, pages_vec].set(vq),
                k_scale.at[:, pages_vec].set(ks),
                v_scale.at[:, pages_vec].set(vs))
    return (k_pages.at[:, pages_vec].set(kb.astype(k_pages.dtype)),
            v_pages.at[:, pages_vec].set(vb.astype(v_pages.dtype)),
            None, None)


def paged_attention_ref(q, k_pages, v_pages, page_table, lens,
                        k_scale=None, v_scale=None, sm_scale=None):
    """jnp reference paged attention (the XLA-fused fallback path and
    the parity pin for the Pallas kernel).

    q [B, Hkv, G, D] (G = query heads per kv head); pages
    [Hkv, P, ps, D]; page_table [B, MP]; lens [B] int32 — keys at
    flat index >= lens[b] are masked. Returns [B, Hkv, G, D].

    Gathers the slot's pages into a dense [B, S_cap, ...] view — the
    reference trades the kernel's in-place HBM reads for clarity; the
    gather is why the Pallas kernel exists at serving batch sizes."""
    b, hkv, g, d = q.shape
    ps = k_pages.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)

    def gather(pages, scale):
        x = pages[:, page_table]        # [Hkv, B, MP, ps, D]
        x = _dequant(x, None if scale is None else scale[:, page_table],
                     jnp.float32)
        x = jnp.moveaxis(x, 1, 0)       # [B, Hkv, MP, ps, D]
        return x.reshape(b, hkv, -1, d)  # [B, Hkv, S_cap, D]

    k = gather(k_pages, k_scale)
    v = gather(v_pages, v_scale)
    s = jnp.einsum("bhgd,bhkd->bhgk", q.astype(jnp.float32), k,
                   preferred_element_type=jnp.float32) * sm_scale
    kpos = jnp.arange(k.shape[2])[None, None, None, :]
    s = jnp.where(kpos < lens[:, None, None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.isnan(p), 0.0, p)  # fully-masked rows -> 0
    out = jnp.einsum("bhgk,bhkd->bhgd", p, v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _rope_rows(x, positions, theta):
    """RoPE for single-token rows: x [B, H, D], positions [B] — the
    per-slot-offset case of llama.apply_rope (ONE shared formula: a
    convention drift between prefill and paged decode would silently
    break K parity)."""
    from .llama import apply_rope
    return apply_rope(x[:, None], positions[:, None], theta)[:, 0]


def paged_layer_forward(q, k, v, cache: PagedLayerCache, out_proj,
                        groups=1, rope_theta=None):
    """The whole per-layer serving branch both GPTAttention and
    LlamaAttention delegate to: Tensor-level dispatch (apply_op) around
    paged_update_and_attend plus the output projection. Returns
    (projected out, new PagedLayerCache)."""
    from ..autograd import apply_op

    def run(qv, kv, vv):
        out, new_pages = paged_update_and_attend(
            qv, kv, vv, cache, groups=groups, rope_theta=rope_theta)
        return (out,) + new_pages

    out, kp, vp, ks, vs = apply_op(run, q, k, v, differentiable=False)
    b, s = out.shape[0], out.shape[1]
    return (out_proj(out.reshape([b, s, -1])),
            cache.replaced(kp, vp, ks, vs))


def paged_update_and_attend(q, k, v, cache: PagedLayerCache, groups=1,
                            rope_theta=None):
    """The per-layer serving step, shared by GPT and Llama attention:
    (optionally RoPE at per-slot positions,) write the new token's K/V
    into the pages, attend the single query row against the slot's
    paged history (self included).

    q [B, 1, H, D]; k/v [B, 1, Hkv, D] raw projections. Returns
    (out [B, 1, H, D], (k_pages, v_pages, k_scale, v_scale)).
    Masked slots (positions route their table row to the trash page —
    the engine's contract) produce zero attention rows; the engine
    discards their sampled tokens."""
    b, sq, h, d = q.shape
    assert sq == 1, "paged decode is the single-token path"
    hkv = k.shape[2]
    assert h == hkv * groups, (h, hkv, groups)
    pos = cache.positions
    q1 = q[:, 0]                        # [B, H, D]
    k1 = k[:, 0]                        # [B, Hkv, D]
    v1 = v[:, 0]
    if rope_theta is not None:
        q1 = _rope_rows(q1, pos, rope_theta)
        k1 = _rope_rows(k1, pos, rope_theta)
    # live-ness is encoded upstream: inactive slots carry an all-trash
    # page table row, so the write is always safe full-width
    live = jnp.ones((b,), jnp.bool_)
    k_pages, v_pages, k_scale, v_scale = write_token_kv(cache, k1, v1,
                                                        live)
    lens = pos + 1                      # the written token attends itself
    qg = q1.reshape(b, hkv, groups, d)
    if cache.use_flash:
        from ..ops.attention import paged_flash_decode
        out = paged_flash_decode(qg, k_pages, v_pages, cache.page_table,
                                 lens, k_scale=k_scale, v_scale=v_scale)
    else:
        out = paged_attention_ref(qg, k_pages, v_pages, cache.page_table,
                                  lens, k_scale=k_scale, v_scale=v_scale)
    out = out.reshape(b, 1, h, d)
    return out, (k_pages, v_pages, k_scale, v_scale)


# -- COW prefix caching (host side) -----------------------------------------
#
# A request whose prompt shares a page-aligned prefix with an earlier
# prompt can reuse that prompt's already-written pages instead of
# recomputing prefill for them. The sharing unit is the FULL page:
# fingerprints are a rolling blake2b chain over page-sized token
# blocks, so a boundary fingerprint commits to the entire token prefix
# before it (two prompts with the same boundary-j fingerprint share
# tokens [0, j*page_size) with cryptographic certainty, and the chain
# is process-independent — the fleet router recomputes the same values
# from heartbeat-advertised page sizes).
#
# COW discipline is structural, not trapped: boundaries stop at
# (len-1)//page_size, so the final prompt position ALWAYS lands in the
# request's private tail (the sampled first token needs a live
# forward), and decode writes land at positions >= len — page index
# len//ps >= any shared boundary — i.e. never on a shared page. The
# "copy" in copy-on-write is the short tail prefill re-materializing
# the partial page privately.


def prefix_fingerprints(prompt, page_size):
    """Rolling per-page-boundary fingerprints of a prompt.

    Returns [fp_1, .., fp_j] hex digests where fp_j commits to tokens
    [0, j*page_size). Boundaries are capped at (len-1)//page_size so
    the final prompt position always stays in the private tail (its
    forward pass samples the first token — see module note above)."""
    arr = np.ascontiguousarray(np.asarray(prompt, np.int64))
    nb = max((arr.shape[0] - 1) // page_size, 0) if arr.shape[0] else 0
    h = hashlib.blake2b(digest_size=12)
    h.update(b"ps%d" % page_size)
    out = []
    for j in range(nb):
        h.update(arr[j * page_size:(j + 1) * page_size].tobytes())
        out.append(h.hexdigest())
    return out


class _PrefixEntry:
    __slots__ = ("fp", "pages", "kv", "hits", "last_used")

    def __init__(self, fp, pages, kv, now):
        self.fp = fp
        self.pages = tuple(pages)   # page ids, boundary order
        self.kv = kv                # [(k, v)] per layer: padded dense
        #                             [1, max_seq_len, Hkv, D] device
        #                             buffers (shared across nested
        #                             boundaries; rows past a boundary
        #                             are overwritten/masked by the
        #                             tail program)
        self.hits = 0
        self.last_used = now


class PrefixIndex:
    """Host-side refcounted index of immutable shared prefix pages.

    One entry per registered page boundary (nested boundaries of the
    same prompt are separate entries sharing page ids and K/V views).
    Two refcounts per owned page: ``owners`` (how many entries cover
    it) and ``rc`` (how many live slots map it). A page returns to the
    engine's free list only when BOTH reach zero — slots release rc on
    finish, entries release owners on LRU eviction, and eviction skips
    any entry with a page still pinned by a live slot (shared pages
    evict LRU only at refcount 0).

    Entries also pin a dense padded copy of the prefix K/V rows (per
    layer, [1, max_seq_len, Hkv, D], built once at registration): the
    tail-prefill program needs the prefix as a dense static-cache
    buffer so the tail's keys/queries attend it exactly as a full
    prefill would, and keeping it device-resident makes a hit
    admission a pure dispatch — zero per-hit transfers. The index
    itself stays engine-agnostic host bookkeeping: the buffers are
    opaque objects it never touches."""

    def __init__(self, page_size, min_pages=1, max_entries=512):
        self.page_size = int(page_size)
        self.min_pages = max(int(min_pages), 1)
        self.max_entries = int(max_entries)
        self._entries = {}      # fp -> _PrefixEntry
        self._owners = {}       # page -> entry count
        self._rc = {}           # page -> live slot count
        self._clock = 0         # monotonic LRU clock (no wall time)
        # counters (plain monotonic ints; the engine surfaces them
        # through health() and the fleet router folds them into the
        # fleet_prefix_* registry series off heartbeats)
        self.hits = 0
        self.misses = 0
        self.hit_pages = 0
        self.total_pages = 0    # shareable prompt pages seen (denom)
        self.cow_copies = 0     # private tail pages re-materialized
        self.evictions = 0
        self.adopted_pages = 0  # pages ever adopted (monotonic; the
        #                         fleet_prefix_shared_pages_total feed
        #                         — shared_pages is the level, this
        #                         the counter)

    # -- introspection ----------------------------------------------------

    @property
    def entries(self):
        return len(self._entries)

    @property
    def owned_pages(self):
        """Pages currently owned by the index (not on the free list)."""
        return set(self._owners)

    @property
    def owned_page_count(self):
        return len(self._owners)

    def pinned(self, page):
        return self._rc.get(page, 0) > 0

    def fingerprint_set(self):
        """All registered boundary fingerprints (heartbeat inventory)."""
        return set(self._entries)

    def covers(self, fps):
        """True when every boundary in the chain is already
        registered (an insert would be a no-op)."""
        return all(fp in self._entries for fp in fps)

    def top_fingerprints(self, n=5):
        """[(fp, pages, hits)] hottest entries, for health()."""
        rows = sorted(self._entries.values(),
                      key=lambda e: (-e.hits, -e.last_used))
        return [(e.fp, len(e.pages), e.hits) for e in rows[:n]]

    def stats(self):
        return {"entries": len(self._entries),
                "shared_pages": len(self._owners),
                "hits": self.hits, "misses": self.misses,
                "hit_pages": self.hit_pages,
                "total_pages": self.total_pages,
                "cow_copies": self.cow_copies,
                "evictions": self.evictions,
                "adopted_pages": self.adopted_pages}

    def sidecar_bytes(self):
        """Device bytes pinned by the dense K/V sidecars, deduplicated
        by object identity (nested boundary entries of one prompt
        share ONE sidecar — counting it per entry would overstate the
        footprint by the nesting depth). The memory ledger's
        prefix_sidecar level reads this."""
        seen, total = set(), 0
        for e in self._entries.values():
            if e.kv is None or id(e.kv) in seen:
                continue
            seen.add(id(e.kv))
            for k, v in e.kv:
                total += int(getattr(k, "nbytes", 0) or 0)
                total += int(getattr(v, "nbytes", 0) or 0)
        return total

    def audit(self, live_refs=None):
        """Cross-check the index's two refcount maps against their
        definitions — the release-on-failover leak detector the
        memory ledger runs every sweep. Returns a list of problem
        strings (empty = consistent); never raises.

        Checks: ``_owners`` must equal per-page coverage recomputed
        from the live entries; ``_rc`` pins must only exist on owned
        pages and must be positive; and, when the engine passes
        ``live_refs`` (page -> count of live slots mapping it via
        slot.shared), ``_rc`` must match it exactly — a pin with no
        live slot is a page that will never return to the free list,
        a live slot without a pin is a page eviction can free under a
        running request."""
        problems = []
        cover = {}
        for e in self._entries.values():
            for p in e.pages:
                cover[p] = cover.get(p, 0) + 1
        if cover != self._owners:
            bad = {p for p in set(cover) | set(self._owners)
                   if cover.get(p, 0) != self._owners.get(p, 0)}
            problems.append(
                f"owner counts diverge from entry coverage on pages "
                f"{sorted(bad)[:8]}")
        for p, n in self._rc.items():
            if n <= 0:
                problems.append(f"non-positive pin {n} on page {p}")
            if p not in self._owners:
                problems.append(f"pin on unowned page {p}")
        if live_refs is not None:
            live = {p: n for p, n in live_refs.items() if n > 0}
            if live != self._rc:
                bad = {p for p in set(live) | set(self._rc)
                       if live.get(p, 0) != self._rc.get(p, 0)}
                problems.append(
                    f"slot pins diverge from live page-table "
                    f"references on pages {sorted(bad)[:8]}")
        return problems

    # -- lookup / refcounting ---------------------------------------------

    def match(self, fps):
        """Longest registered boundary of a fingerprint chain:
        (entry, npages) or None. A boundary hit implies every shorter
        boundary matches too (rolling chain), so scanning from the
        longest suffices; respects min_pages."""
        for j in range(len(fps), self.min_pages - 1, -1):
            e = self._entries.get(fps[j - 1])
            if e is not None:
                return e, j
        return None

    def acquire(self, entry):
        """Pin an entry's pages for a live slot; returns the page ids
        in boundary order."""
        self._clock += 1
        entry.hits += 1
        entry.last_used = self._clock
        for p in entry.pages:
            self._rc[p] = self._rc.get(p, 0) + 1
        return list(entry.pages)

    def release(self, pages):
        """Drop a finished slot's pin on shared pages. Pages stay owned
        by their entries (reuse is the point) — only eviction frees."""
        for p in pages:
            n = self._rc.get(p, 0) - 1
            if n > 0:
                self._rc[p] = n
            else:
                self._rc.pop(p, None)

    # -- registration / eviction ------------------------------------------

    def insert(self, fps, pages, kv, *, pin=True):
        """Register boundaries [min_pages .. len(fps)] of a prompt.

        ``pages`` are the donor slot's prompt pages (>= len(fps) of
        them); ``kv`` is the padded dense K/V sidecar ([(k, v)] per
        layer, [1, max_seq_len, Hkv, D]) — one object, shared by
        every nested boundary entry (rows past a boundary are
        overwritten/masked by the tail program, so no per-boundary
        slices exist). Pages newly adopted by the index get rc pinned
        for the donor slot when ``pin`` (the slot is still running on
        them; its release drops the pin). Returns (adopted, freed):
        the set of pages the index now owns among
        ``pages[:len(fps)]``, and pages released by capacity eviction
        that the caller MUST return to its free list."""
        adopted, freed = set(), []
        self._clock += 1
        for j in range(self.min_pages, len(fps) + 1):
            fp = fps[j - 1]
            if fp in self._entries:
                self._entries[fp].last_used = self._clock
                continue
            if len(self._entries) >= self.max_entries and \
                    not self._evict_entries(1, freed):
                break               # full and nothing evictable
            self._entries[fp] = _PrefixEntry(fp, pages[:j], kv,
                                             self._clock)
            for p in pages[:j]:
                if p not in self._owners:
                    adopted.add(p)
                self._owners[p] = self._owners.get(p, 0) + 1
        self.adopted_pages += len(adopted)
        if pin:
            for p in adopted:
                self._rc[p] = self._rc.get(p, 0) + 1
        return adopted, freed

    def evict(self, need_pages):
        """Free at least ``need_pages`` pages by LRU entry eviction
        (entries whose pages are all slot-unpinned). Returns the list
        of freed page ids (may be shorter than asked)."""
        freed = []
        while len(freed) < need_pages:
            got = self._evict_entries(1, freed)
            if not got:
                break
        return freed

    def _evict_entries(self, n, freed=None):
        """Evict up to n LRU entries with no slot-pinned page; append
        fully-released pages to ``freed``. Returns entries evicted."""
        done = 0
        for e in sorted(self._entries.values(),
                        key=lambda e: e.last_used):
            if done >= n:
                break
            if any(self._rc.get(p, 0) for p in e.pages):
                continue
            del self._entries[e.fp]
            self.evictions += 1
            done += 1
            for p in e.pages:
                left = self._owners.get(p, 0) - 1
                if left > 0:
                    self._owners[p] = left
                else:
                    self._owners.pop(p, None)
                    if freed is not None:
                        freed.append(p)
        return done
