"""Speculative decoding proposers for the paged serving engine.

The engine's speculative loop (nlp/serving.py `_dispatch_spec`) is
propose -> one folded verify dispatch -> host commit/rewind. THIS
module is the propose half: a proposer drafts ``spec_k`` candidate
tokens per slot each round; the target model then scores all K+1
positions in ONE batched dispatch and commits exactly the prefix its
own per-position seeded sampler reproduces. The contract that makes
any proposer safe to plug in:

- **draft quality is a latency knob, never a correctness one** — a
  proposer that emits garbage costs acceptance (and therefore tok/s),
  but every committed token still comes out of the TARGET's sampler
  with the TARGET's per-(request, index) key, bit-identical to plain
  decode;
- **propose() is called between dispatches** and may not mutate any
  target-engine state (page table, seq lens, RNG) — the engine owns
  the commit; a proposer owns only its private state;
- **zero-recompile holds**: any program a proposer compiles is traced
  inside ``warmup()`` through the engine's counting jit, so the
  post-warmup frozen-counts assertion covers draft programs too.

Two proposers ship:

``NgramProposer`` (default, ``spec_draft="ngram"``) — zero-weight
prompt-lookup speculation: the longest recent-suffix n-gram of each
slot's (prompt + generated) stream is matched against its own earlier
occurrences and the K tokens that followed the most recent match are
proposed (vLLM's "prompt lookup" / ngram speculation). Needs no
second model, no device state, no warmup work — pure host numpy —
and wins exactly on the repetitive/extractive traffic where drafting
pays at all.

``DraftModelProposer`` (``spec_draft="gpt-tiny"`` etc. or a model
instance) — a small GPT/Llama sharing the target's tokenizer drafts
autoregressively through its OWN paged KV pool (fixed identity page
table — one private lane of pages per slot, so no allocator and no
interaction with the target's free list). The draft never rewinds:
its state is DERIVED from the target's each round by a uniform
(K+1)-step scan — step 0 re-ingests token index L-1 (idempotent
rewrite of a row the draft already holds), step 1 is forced to the
target's last committed token (index L), steps 2..K consume the
draft's own proposals — so after any accept/reject pattern the rows
a future round attends are exactly the committed stream's.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer import functional_call
from ..tensor import Tensor
from .paged_cache import PagedLayerCache, alloc_pages, \
    write_prompt_kv, TRASH_PAGE

__all__ = ["NgramProposer", "DraftModelProposer", "make_proposer"]


def _ngram_propose(ctx, k, pad, nmin=1, nmax=3):
    """Prompt-lookup drafts for one stream: match the longest suffix
    n-gram (nmax down to nmin) at its MOST RECENT earlier occurrence
    and propose the tokens that followed it. A match near the end of
    the context SELF-EXTENDS — drafted tokens join the working
    context and the lookup repeats — so a tight cycle drafts all k
    tokens instead of padding after one period. ``pad`` fills only
    when no n-gram recurs at all. Pure host work, O(n * len^2) worst
    case — fine at serving prompt lengths."""
    work = list(ctx)
    out = []
    while len(out) < k:
        got = None
        n_ctx = len(work)
        for n in range(min(nmax, n_ctx - 1), nmin - 1, -1):
            suf = work[n_ctx - n:]
            for s in range(n_ctx - n - 1, -1, -1):
                if work[s:s + n] == suf:
                    got = work[s + n:s + n + (k - len(out))]
                    break
            if got:
                break
        if not got:
            break
        out.extend(got)
        work.extend(got)
    out.extend([pad] * (k - len(out)))
    return out[:k]


class NgramProposer:
    """Zero-weight prompt-lookup proposer (see module doc)."""

    kind = "ngram"

    def __init__(self, engine, nmin=1, nmax=3):
        self.nmin = int(nmin)
        self.nmax = int(nmax)
        del engine  # stateless: everything is read at propose time

    def warmup(self, engine, buckets):
        """Nothing to trace — host numpy only."""

    def on_admit(self, engine, b, req):
        """No per-admission state."""

    def propose(self, engine):
        """[max_slots, spec_k] int32 drafts; dead slots get pad rows
        (their verify lanes are ignored by the commit loop)."""
        k = engine.spec_k
        pad = engine.pad_token_id
        drafts = np.full((engine.max_slots, k), pad, np.int32)
        for b in range(engine.max_slots):
            slot = engine._slots[b]
            if slot is None or not engine._active[b] \
                    or engine._done[b]:
                continue
            ctx = list(slot.req.prompt) + list(slot.out_tokens)
            drafts[b] = _ngram_propose(ctx, k, pad,
                                       self.nmin, self.nmax)
        return drafts


class DraftModelProposer:
    """Small-model proposer over a private paged KV pool (module doc).

    The draft pool mirrors the target's page geometry but with a FIXED
    identity page table: slot ``b`` owns pages
    ``[1 + b*pps, 1 + (b+1)*pps)`` (page 0 is the draft's own trash
    page), so admission/eviction never touches a draft allocator.
    Rows the propose scan would write past ``max_seq_len`` are
    redirected to the trash page with the position clamped — those
    proposals are junk, which only costs acceptance near the length
    cap (the verify program independently trash-guards its side).
    """

    kind = "draft"

    def __init__(self, engine, model):
        model.eval()
        self.model = model
        cfg = model.config
        if cfg.vocab_size != engine.cfg.vocab_size:
            raise ValueError(
                f"draft model vocab_size={cfg.vocab_size} != target "
                f"vocab_size={engine.cfg.vocab_size}: speculative "
                "drafts must share the tokenizer")
        self.kv_heads = (getattr(cfg, "num_key_value_heads", 0)
                         or cfg.num_attention_heads)
        self.num_layers = cfg.num_hidden_layers
        self.head_dim = cfg.head_dim
        self._params, self._buffers = model.raw_state()
        b = engine.max_slots
        ps = engine.page_size
        pps = engine.max_pages_per_seq
        # draft pool: f32 regardless of the target's cache dtype (the
        # draft is tiny; its numerics never reach committed tokens)
        self._pages = [alloc_pages(1 + b * pps, ps, self.kv_heads,
                                   self.head_dim, "float32")
                       for _ in range(self.num_layers)]
        if getattr(engine, "ledger", None) is not None:
            # draft pool + draft weights land in the engine's memory
            # ledger at the allocation seam (spec_draft_pool segment)
            engine.ledger.track(
                "spec_draft_pool", self._pages,
                label=f"model={type(model).__name__}")
            engine.ledger.track(
                "weights", (self._params, self._buffers),
                label=f"model={type(model).__name__},role=draft")
        self._table = np.arange(b * pps, dtype=np.int32) \
            .reshape(b, pps) + 1
        self._prefill_fns = {}
        self._warmed_buckets = set()
        self._propose_fn = None

    # -- compiled programs (traced via the ENGINE's counting jit, so
    # draft traces land in the same compile budget / frozen-counts
    # assertion as every serving program) --------------------------

    def _layer_caches(self, pages, page_table, positions):
        return [PagedLayerCache(k, v, page_table, positions,
                                k_scale=ks, v_scale=vs,
                                use_flash=False)
                for (k, v, ks, vs) in pages]

    def _token_step(self, params, buffers, pages, tokens, page_table,
                    positions):
        caches = self._layer_caches(pages, page_table, positions)
        out = functional_call(
            self.model, params, buffers,
            Tensor(tokens[:, None]), use_cache=False, cache=caches,
            cache_index=Tensor(positions))
        logits_t, new_caches = out
        logits = logits_t._value if isinstance(logits_t, Tensor) \
            else logits_t
        from .serving import ServingEngine
        return (logits[:, -1].astype(jnp.float32),
                ServingEngine._unwrap_pages(new_caches))

    def _prefill_fn(self, engine, bucket):
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn

        def dprefill(params, buffers, pages, ids, true_len, pages_vec):
            s_b = ids.shape[1]
            mask = (jnp.arange(s_b)[None, :]
                    < true_len).astype(jnp.int32)
            out = functional_call(self.model, params, buffers,
                                  Tensor(ids),
                                  attention_mask=Tensor(mask),
                                  use_cache=True)
            _logits, caches = out

            def arr(x):
                return x._value if isinstance(x, Tensor) else x

            new_pages = []
            for (k, v, ks, vs), layer in zip(pages, caches):
                new_pages.append(write_prompt_kv(
                    k, v, ks, vs, arr(layer[0]), arr(layer[1]),
                    pages_vec))
            return new_pages

        fn = engine._counting(f"draft_prefill_{bucket}", dprefill,
                              donate_argnums=(2,))
        self._prefill_fns[bucket] = fn
        return fn

    def _build_propose_fn(self, engine):
        k1 = engine.spec_k + 1
        max_len = engine.max_seq_len

        def propose(params, buffers, pages, page_table, lens, last0,
                    next_tok):
            # one-behind protocol: lens = L-1 (L = the target's
            # committed length), so step i writes draft row L-1+i.
            # step 0 input = token index L-1 (idempotent rewrite),
            # step 1 FORCED to the target's last token (index L),
            # steps 2..K consume the previous step's proposal.
            def step(carry, i):
                pages, prev = carry
                tok = jnp.where(i == 0, last0,
                                jnp.where(i == 1, next_tok, prev))
                pos = lens + i
                pt = jnp.where((pos >= max_len)[:, None],
                               jnp.int32(TRASH_PAGE), page_table)
                pos_c = jnp.minimum(pos, max_len - 1)
                logits, pages = self._token_step(
                    params, buffers, pages, tok, pt, pos_c)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (pages, nxt), nxt

            (pages, _), props = jax.lax.scan(
                step, (pages, last0), jnp.arange(k1, dtype=jnp.int32))
            # props[i] is the proposal emitted by step i; step 0's is
            # a throwaway (its true successor is already known: the
            # forced next_tok) -> drafts = props[1:], [K, B] -> [B, K]
            return props[1:].T, pages

        return engine._counting("draft_propose", propose,
                                donate_argnums=(2,))

    # -- proposer interface ----------------------------------------

    def warmup(self, engine, buckets):
        """Trace the draft prefill per (normalized) bucket plus the
        propose scan — called from the engine's warmup() after the
        target programs, writes landing in the draft's trash page."""
        for n in buckets:
            if n in self._warmed_buckets:
                continue
            fn = self._prefill_fn(engine, n)
            ids = np.full((1, n), engine.pad_token_id, np.int32)
            pages_vec = np.full((n // engine.page_size,), TRASH_PAGE,
                                np.int32)
            self._pages = fn(self._params, self._buffers, self._pages,
                            jnp.asarray(ids), jnp.int32(1),
                            jnp.asarray(pages_vec))
            self._warmed_buckets.add(n)
        if self._propose_fn is None:
            b = engine.max_slots
            self._propose_fn = self._build_propose_fn(engine)
            _drafts, new_pages = self._propose_fn(
                self._params, self._buffers, self._pages,
                jnp.asarray(np.full_like(self._table, TRASH_PAGE)),
                jnp.asarray(np.zeros((b,), np.int32)),
                jnp.asarray(np.zeros((b,), np.int32)),
                jnp.asarray(np.zeros((b,), np.int32)))
            self._pages = new_pages

    def on_admit(self, engine, b, req):
        """Ingest the freshly admitted prompt into slot ``b``'s draft
        lane. An unwarmed bucket is skipped (never a mid-traffic
        compile): the lane then holds stale rows and this slot's
        proposals are junk until re-admission — acceptance cost only.
        """
        bucket = engine._bucket_for(len(req.prompt))
        if bucket not in self._warmed_buckets:
            return
        ps = engine.page_size
        nb = bucket // ps
        pages_vec = np.full((nb,), TRASH_PAGE, np.int32)
        pages_vec[:nb] = self._table[b, :nb]
        ids = np.full((1, bucket), engine.pad_token_id, np.int32)
        ids[0, :len(req.prompt)] = req.prompt
        fn = self._prefill_fn(engine, bucket)
        self._pages = fn(self._params, self._buffers, self._pages,
                         jnp.asarray(ids),
                         jnp.int32(len(req.prompt)),
                         jnp.asarray(pages_vec))

    def propose(self, engine):
        if self._propose_fn is None:     # never warmed: junk drafts
            return np.full((engine.max_slots, engine.spec_k),
                           engine.pad_token_id, np.int32)
        b = engine.max_slots
        lens = np.maximum(engine._seq_lens - 1, 0).astype(np.int32)
        last0 = np.zeros((b,), np.int32)
        for i in range(b):
            slot = engine._slots[i]
            if slot is None or not engine._active[i] \
                    or engine._done[i]:
                continue
            # token index L-1: the last prompt token until the second
            # generated token exists, then the second-to-last output
            last0[i] = slot.req.prompt[-1] \
                if len(slot.out_tokens) <= 1 else slot.out_tokens[-2]
        drafts, new_pages = self._propose_fn(
            self._params, self._buffers, self._pages,
            jnp.asarray(self._table), jnp.asarray(lens),
            jnp.asarray(last0),
            jnp.asarray(engine._last_tokens.astype(np.int32)))
        self._pages = new_pages
        return np.asarray(drafts).astype(np.int32)


def make_proposer(engine, draft):
    """Resolve the engine's ``spec_draft`` knob: "ngram" (default) ->
    NgramProposer; a tiny-config name ("gpt-tiny", "llama-tiny", any
    name the GPT/Llama config resolvers know) -> a freshly seeded
    DraftModelProposer; a model INSTANCE -> DraftModelProposer over
    it (the way to hand in actually trained draft weights)."""
    if draft is None or draft == "ngram":
        return NgramProposer(engine)
    if not isinstance(draft, str):
        return DraftModelProposer(engine, draft)
    name = draft.lower()
    if name.startswith("gpt"):
        from .gpt import GPTForCausalLM, _resolve_config
        model = GPTForCausalLM(_resolve_config(name))
    elif name.startswith("llama"):
        from .llama import LlamaForCausalLM, _resolve_config
        model = LlamaForCausalLM(_resolve_config(name))
    else:
        raise ValueError(
            f"spec_draft {draft!r}: expected 'ngram', a gpt*/llama* "
            "config name, or a model instance")
    return DraftModelProposer(engine, model)
