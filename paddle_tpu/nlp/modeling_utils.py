"""Shared transformer modeling helpers (BERT/ERNIE/GPT).

ref: the mask preparation logic every PaddleNLP model repeats in
modeling.py (_prepare_decoder_attention_mask / get_extended_attention_mask).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor import Tensor


def normalize_attention_mask(attention_mask):
    """Normalise a user attention mask to [b, 1, sq|1, sk] broadcastable
    form: 2D/3D 0/1 padding masks (int or float — the tokenizer
    convention) become bool keep-masks; 4D float masks pass through as
    additive biases (paddle.nn.functional sdpa semantics)."""
    if attention_mask is None:
        return None
    m = attention_mask._value if isinstance(attention_mask, Tensor) \
        else jnp.asarray(attention_mask)
    is_padding = m.ndim <= 3
    if m.ndim == 2:
        m = m[:, None, None, :]
    elif m.ndim == 3:
        m = m[:, None]
    if m.dtype != jnp.bool_ and is_padding:
        m = m != 0
    return Tensor(m)


def fused_residual_ln(residual, h, ln, want_sum=True):
    """LN(residual + h) scaled/shifted by `ln`'s params in ONE Pallas
    pass (ops/pallas/fused_ln.py) — the add->reduce boundary XLA keeps
    as separate HBM round trips. want_sum=True returns (y, s) with
    s = residual + h materialized (GPT pre-LN: s feeds the next
    residual); want_sum=False returns y alone and skips the sum's HBM
    write entirely (BERT/ERNIE post-LN discard it). interpret off-TPU."""
    import jax as _jax

    from ..autograd import apply_op
    from ..ops.pallas.fused_ln import (fused_add_layer_norm,
                                       fused_add_layer_norm_y)
    interp = _jax.default_backend() != "tpu"
    eps = getattr(ln, "_epsilon", 1e-5)
    fn = fused_add_layer_norm if want_sum else fused_add_layer_norm_y
    return apply_op(
        lambda a, b, g, bb: fn(a, b, g, bb, eps, 0, interp),
        residual, h, ln.weight, ln.bias)


def from_pretrained_impl(cls, resolve, name_or_path, pretrained_path=None,
                         config_name=None, **overrides):
    """PaddleNLP `Model.from_pretrained` parity for an offline
    environment (ref: paddlenlp.transformers PretrainedModel
    .from_pretrained, which downloads by name).

    Accepted forms:
      from_pretrained('bert-base-uncased')                -> config only;
        weights need a local file, so this raises with the
        convert-and-load recipe.
      from_pretrained('bert-base-uncased',
                      pretrained_path='bert.pdparams')    -> build from
        the named config, then load the checkpoint (reference .pdparams
        pickles or paddle_tpu saves both load).
      from_pretrained('/path/ckpt.pdparams',
                      config_name='bert-base-uncased')    -> same, with
        the checkpoint path first.
    """
    import os
    name = name_or_path
    if os.path.exists(str(name_or_path)):
        if pretrained_path is not None:
            raise ValueError(
                f"'{name_or_path}' is a checkpoint path AND "
                f"pretrained_path='{pretrained_path}' was given — pass "
                "exactly one weights source")
        if not config_name:
            raise ValueError(
                f"'{name_or_path}' is a checkpoint path; also pass "
                "config_name='<config>' so the architecture can be "
                "built before loading the weights")
        pretrained_path, name = str(name_or_path), config_name
    model = cls(resolve(name, **overrides))
    if pretrained_path is None:
        raise NotImplementedError(
            f"from_pretrained('{name}') needs a weights download, which "
            "this offline environment cannot do. Recipe: in the "
            "reference framework run `paddle.save(model.state_dict(), "
            f"'{name}.pdparams')`, copy the file here, and call "
            f"from_pretrained('{name}', pretrained_path='"
            f"{name}.pdparams') — the .pdparams pickle loads directly "
            "(paddle_tpu.compat.load_pdparams)")
    from ..serialization import load
    state = load(str(pretrained_path))
    if isinstance(state, dict) and set(state) >= {"params"} and \
            all(k in ("params", "buffers", "specs") for k in state):
        state = {**state.get("params", {}), **state.get("buffers", {})}
    state = adapt_state_for_model(model, state)
    # strict, like serialization.load_into (which would re-read the
    # file — at 1.3B scale that is gigabytes of redundant unpickling)
    missing = [k for k in model.state_dict() if k not in state]
    if missing:
        raise ValueError(
            f"checkpoint {pretrained_path} (after layout conversion) "
            f"is missing parameters "
            f"{missing[:8]}{'...' if len(missing) > 8 else ''} — "
            "refusing a partial load")
    model.set_state_dict(state)
    return model


def adapt_state_for_model(model, state):
    """Bridge checkpoint layouts to the built model's: unrolled
    per-layer keys <-> scan-stacked [L, ...] leaves
    (config.scan_layers), and separate q/k/v projections <-> the fused
    Megatron-interleaved qkv_proj (config.fused_qkv) — both directions,
    composing (a stacked-fused model loads a plain reference
    checkpoint and vice versa). Returns `state` unchanged when the
    layouts already agree. ref: paddlenlp PretrainedModel's
    convert-from-other-layout hooks (from_pretrained does the
    equivalent bridging for torch-layout weights)."""
    cfg = getattr(model, "config", None)
    if cfg is None or not isinstance(state, dict) or not state:
        return state
    from ..nn.scan_stack import stack_layer_state, unstack_layer_state
    from .gpt import fuse_qkv_state, split_qkv_state
    L = getattr(cfg, "num_hidden_layers", None)
    heads = getattr(cfg, "num_attention_heads", None)

    def stacked_prefix(keys):
        for k in keys:
            if "__" in k:
                head = k.split("__", 1)[0]
                return head.rsplit(".", 1)[0] + "." if "." in head else ""
        return None

    model_keys = list(model.state_dict())
    m_stacked = stacked_prefix(model_keys)
    orig = state
    c_stacked = stacked_prefix(state)
    if c_stacked is not None and m_stacked is None and L:
        state = unstack_layer_state(state, L, prefix=c_stacked)
    want_fused = any(".qkv_proj." in k or "qkv_proj__" in k
                     for k in model_keys)
    have_sep = any(".q_proj." in k for k in state)
    have_fused = any(".qkv_proj." in k for k in state)
    if heads and want_fused and have_sep and not have_fused:
        state = fuse_qkv_state(state, heads)
    elif heads and not want_fused and have_fused:
        state = split_qkv_state(state, heads)
    if m_stacked is not None and stacked_prefix(state) is None and L:
        state = stack_layer_state(state, L, prefix=m_stacked)
    # if nothing changed semantically, hand back the original object so
    # the caller can fall through to the plain strict load
    return state if state is not orig else orig


class FromPretrainedMixin:
    """One from_pretrained for every model family: resolves the config
    resolver from cls._resolve (task heads) or the defining module's
    _resolve_config (backbones)."""

    @classmethod
    def from_pretrained(cls, name_or_path, pretrained_path=None,
                        config_name=None, **overrides):
        import sys
        resolve = getattr(cls, "_resolve", None)
        if resolve is None:
            resolve = getattr(sys.modules[cls.__module__],
                              "_resolve_config")
        return from_pretrained_impl(cls, resolve, name_or_path,
                                    pretrained_path, config_name,
                                    **overrides)
