"""Shared transformer modeling helpers (BERT/ERNIE/GPT).

ref: the mask preparation logic every PaddleNLP model repeats in
modeling.py (_prepare_decoder_attention_mask / get_extended_attention_mask).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor import Tensor


def normalize_attention_mask(attention_mask):
    """Normalise a user attention mask to [b, 1, sq|1, sk] broadcastable
    form: 2D/3D 0/1 padding masks (int or float — the tokenizer
    convention) become bool keep-masks; 4D float masks pass through as
    additive biases (paddle.nn.functional sdpa semantics)."""
    if attention_mask is None:
        return None
    m = attention_mask._value if isinstance(attention_mask, Tensor) \
        else jnp.asarray(attention_mask)
    is_padding = m.ndim <= 3
    if m.ndim == 2:
        m = m[:, None, None, :]
    elif m.ndim == 3:
        m = m[:, None]
    if m.dtype != jnp.bool_ and is_padding:
        m = m != 0
    return Tensor(m)
