"""Shared transformer modeling helpers (BERT/ERNIE/GPT).

ref: the mask preparation logic every PaddleNLP model repeats in
modeling.py (_prepare_decoder_attention_mask / get_extended_attention_mask).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor import Tensor


def normalize_attention_mask(attention_mask):
    """Normalise a user attention mask to [b, 1, sq|1, sk] broadcastable
    form: 2D/3D 0/1 padding masks (int or float — the tokenizer
    convention) become bool keep-masks; 4D float masks pass through as
    additive biases (paddle.nn.functional sdpa semantics)."""
    if attention_mask is None:
        return None
    m = attention_mask._value if isinstance(attention_mask, Tensor) \
        else jnp.asarray(attention_mask)
    is_padding = m.ndim <= 3
    if m.ndim == 2:
        m = m[:, None, None, :]
    elif m.ndim == 3:
        m = m[:, None]
    if m.dtype != jnp.bool_ and is_padding:
        m = m != 0
    return Tensor(m)


def from_pretrained_impl(cls, resolve, name_or_path, pretrained_path=None,
                         config_name=None, **overrides):
    """PaddleNLP `Model.from_pretrained` parity for an offline
    environment (ref: paddlenlp.transformers PretrainedModel
    .from_pretrained, which downloads by name).

    Accepted forms:
      from_pretrained('bert-base-uncased')                -> config only;
        weights need a local file, so this raises with the
        convert-and-load recipe.
      from_pretrained('bert-base-uncased',
                      pretrained_path='bert.pdparams')    -> build from
        the named config, then load the checkpoint (reference .pdparams
        pickles or paddle_tpu saves both load).
      from_pretrained('/path/ckpt.pdparams',
                      config_name='bert-base-uncased')    -> same, with
        the checkpoint path first.
    """
    import os
    name = name_or_path
    if os.path.exists(str(name_or_path)):
        if pretrained_path is not None:
            raise ValueError(
                f"'{name_or_path}' is a checkpoint path AND "
                f"pretrained_path='{pretrained_path}' was given — pass "
                "exactly one weights source")
        if not config_name:
            raise ValueError(
                f"'{name_or_path}' is a checkpoint path; also pass "
                "config_name='<config>' so the architecture can be "
                "built before loading the weights")
        pretrained_path, name = str(name_or_path), config_name
    model = cls(resolve(name, **overrides))
    if pretrained_path is None:
        raise NotImplementedError(
            f"from_pretrained('{name}') needs a weights download, which "
            "this offline environment cannot do. Recipe: in the "
            "reference framework run `paddle.save(model.state_dict(), "
            f"'{name}.pdparams')`, copy the file here, and call "
            f"from_pretrained('{name}', pretrained_path='"
            f"{name}.pdparams') — the .pdparams pickle loads directly "
            "(paddle_tpu.compat.load_pdparams)")
    from ..serialization import load_into
    load_into(model, pretrained_path)
    return model


class FromPretrainedMixin:
    """One from_pretrained for every model family: resolves the config
    resolver from cls._resolve (task heads) or the defining module's
    _resolve_config (backbones)."""

    @classmethod
    def from_pretrained(cls, name_or_path, pretrained_path=None,
                        config_name=None, **overrides):
        import sys
        resolve = getattr(cls, "_resolve", None)
        if resolve is None:
            resolve = getattr(sys.modules[cls.__module__],
                              "_resolve_config")
        return from_pretrained_impl(cls, resolve, name_or_path,
                                    pretrained_path, config_name,
                                    **overrides)
