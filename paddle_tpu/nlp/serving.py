"""Continuous-batching serving engine — paged KV cache + batched decode.

ref parity: FastDeploy / vLLM-style continuous batching over the
PaddleNLP generation surface (the reference serves GPT/Llama through
fused block-attention CUDA ops; see PAPERS.md on memory-efficient
attention serving). TPU-native design: EVERYTHING the chip executes is
one of a small, fixed set of compiled XLA programs —

- ONE batched decode program per sampling strategy: a `lax.scan` of
  `steps_per_dispatch` single-token steps over the whole slot pool
  (single dispatch per K tokens x B slots), paged-cache reads/writes
  inside (nlp/paged_cache.py; Pallas GQA flash-decode when armed);
- ONE prefill program per power-of-two length bucket: admission pads
  the prompt to the bucket, masks the tail, and scatters the prompt's
  K/V into the slot's pages — a new request NEVER triggers a fresh
  trace once its bucket is warm;
- page allocation, slot assignment, admission and eviction are
  host-side bookkeeping BETWEEN dispatches (a free-list of page ids
  and a [slots, max_pages] int32 table) — they change array CONTENTS,
  never shapes, so the steady state compiles nothing.

Zero-recompile is not aspirational: every jitted program runs under a
trace counter and `compile_counts()` exposes them; `bench.py --serve`
asserts the counts freeze after warmup on every ladder rung.

The cache is shared GPT/Llama (both models' attention layers route a
`PagedLayerCache` through `paged_update_and_attend`): GQA models cache
only their kv heads; `cache_dtype` float32/bfloat16/int8 trades HBM
decode bandwidth for precision (int8 carries per-token-per-head f32
scale sidecars).

Degradation under load is first-class (docs/robustness.md): per-
request deadlines and cancel() resolve at host step boundaries (never
mid-dispatch, never a recompile), admission back-pressure can reject
or evict-lowest-priority when KV pages run out, a resilience.Watchdog
flags wedged dispatches, transient dispatch errors ride a bounded
retry, and health() exposes the whole picture. Every path drills
deterministically via resilience.faults (page_exhaustion, slow_step,
dispatch_error).

Single-threaded by design (one engine owns one chip's decode loop);
wrap submissions in your own queue for multi-producer serving — or
run N engines as a fault-tolerant fleet behind
``serving_fleet.FleetRouter`` (health-routed balancing, failover with
token-exact prefix dedup, hedging, graceful drain/rejoin via
``drain()``/``resume()``/``export_inflight()`` below).
"""
from __future__ import annotations

import collections
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer import functional_call
from ..observability.metrics import MetricsRegistry
from ..resilience import faults
from ..resilience.retry import call_with_retries
from ..tensor import Tensor
from .paged_cache import PagedLayerCache, PrefixIndex, alloc_pages, \
    prefix_fingerprints, write_prompt_kv, TRASH_PAGE

__all__ = ["ServingEngine", "ServeRequest"]


class ServeRequest:
    """One queued generation request.

    deadline: absolute time.monotonic() seconds (None = no deadline) —
    checked at host step boundaries only, preserving zero-recompile.
    priority: larger = more important; the evict admission policy may
    preempt a strictly-lower-priority running request.
    """

    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_token_id",
                 "deadline", "priority", "submitted_at", "submitted_pc",
                 "trace", "admitted_pc", "tenant", "queue_wait_s",
                 "prefix_fps")

    def __init__(self, rid, prompt, max_new_tokens, eos_token_id,
                 deadline=None, priority=0, trace=None, tenant=None):
        self.rid = rid
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.deadline = deadline
        self.priority = int(priority)
        self.submitted_at = time.monotonic()
        # span clock (perf_counter): the queue-wait span's start
        self.submitted_pc = time.perf_counter()
        # distributed-trace context (observability.dtrace wire form);
        # None for untraced (non-fleet) requests — zero overhead then
        self.trace = trace
        self.admitted_pc = None
        # tenancy label (observability.tenancy): None = untagged, no
        # accounting; set at admission so finish sees the real wait
        self.tenant = None if tenant is None else str(tenant)
        self.queue_wait_s = None
        # rolling per-page-boundary fingerprint chain (COW prefix
        # caching) — computed once at submit when the cache is on
        self.prefix_fps = None


class _Slot:
    __slots__ = ("req", "pages", "out_tokens", "status", "admit_seq",
                 "decode_t0", "shared", "prefix_hit_pages",
                 "prefix_pages", "spec_proposed", "spec_accepted")

    def __init__(self, req, pages, admit_seq=0):
        self.req = req
        self.pages = pages          # page ids owned by this sequence
        self.out_tokens = []        # generated tokens (host ints)
        self.status = "ok"          # ok | expired | cancelled | evicted
        self.admit_seq = admit_seq  # admission order (evict tie-break)
        self.decode_t0 = None       # perf_counter at prefill end (the
        #                             traced decode leg's start)
        self.shared = frozenset()   # pages owned by the prefix index
        #                             (release, don't free, on finish)
        self.prefix_hit_pages = 0   # prompt pages served from cache
        self.prefix_pages = 0       # shareable prompt pages (denom)
        self.spec_proposed = 0      # draft tokens dispatched to verify
        self.spec_accepted = 0      # draft tokens the target confirmed


def _next_pow2(n):
    return 1 << max(0, (int(n) - 1)).bit_length()


class ServingEngine:
    """Continuous-batching decode over a fixed slot pool.

    model: GPTForCausalLM / LlamaForCausalLM (anything whose attention
    layers understand the PagedLayerCache contract). All requests share
    one sampling strategy (greedy when temperature==0, else
    temperature/top-k sampling) — the strategy is baked into the one
    compiled decode program.

    max_slots: decode batch width (the slot pool).
    page_size: tokens per KV page (multiple of 8).
    max_seq_len: per-sequence capacity (prompt + generated), rounded up
        to whole pages; fixes the page-table width.
    num_pages: total pool pages (page 0 is the reserved trash page).
        Default fully provisions every slot; smaller values exercise
        admission back-pressure/recycling.
    cache_dtype: 'float32' | 'bfloat16' | 'int8' KV storage.
    use_flash: None auto (TPU + PADDLE_TPU_FLASH_DECODE=1), True force
        the Pallas paged kernel (interpret mode off-TPU), False jnp ref.
    steps_per_dispatch: decode tokens per compiled call (the scan
        length) — admission/eviction happen at dispatch boundaries.
    admission_policy: what to do with the queue head when pages run
        out — 'wait' (back-pressure, retry next boundary), 'reject'
        (finish it immediately with status='rejected'), or 'evict'
        (preempt the lowest-priority strictly-lower-priority running
        request, finishing it with status='evicted' and its partial
        tokens; falls back to waiting when no such victim exists).
    watchdog_timeout: seconds; when set, a resilience.Watchdog daemon
        monitors every decode/prefill dispatch and flags a wedge in
        health() when one stays in flight past the timeout (it cannot
        cancel a running XLA execute — detection only).
    dispatch_retries: bounded deterministic backoff for transient
        RESOURCE_EXHAUSTED-style dispatch errors (resilience.retry).
    registry: observability.MetricsRegistry the engine publishes its
        serve_* series into (docs/observability.md metric catalogue);
        default a PRIVATE per-engine registry, so two engines in one
        process never alias each other's counters and reset_counters()
        on one cannot zero another's window — pass
        observability.metrics.get_registry() (or merge
        engine.registry.snapshot()) to land the series in the
        process-global export. Everything is recorded at host step
        boundaries AFTER the dispatch's existing device sync —
        instrumentation adds no host sync and no trace inputs, so the
        zero-recompile contract is untouched. reset_counters() zeroes
        every serve_* series (incl. retry/watchdog counts) uniformly.
    donate: donate the page pool to the decode/prefill programs
        (in-place HBM updates). Turn OFF when running under a
        persistent compilation cache on jax 0.4.x (reloading donated
        executables aborts — R6_NOTES.md); bench.py does this
        automatically for PADDLE_TPU_BENCH_CACHE.
    prefix_cache: copy-on-write prefix-page sharing (PrefixIndex):
        prompts sharing a page-aligned prefix with an earlier prompt
        map the already-written pages into their page table and run a
        short bucketed TAIL prefill only. Hits can change TTFT, never
        tokens (docs/performance.md round 19). Default ON; None reads
        PADDLE_TPU_PREFIX_CACHE (0/false/off disables — the kill
        switch). Hit admission additionally requires the tail bucket
        pre-traced by warmup() — a cold engine serves every request
        through the full-prefill path, so zero-recompile and token
        goldens hold unconditionally.
    min_prefix_pages: shortest prefix (in whole pages) worth sharing;
        None reads PADDLE_TPU_PREFIX_MIN_PAGES (default 1).
    prefix_max_entries: bound on registered fingerprint boundaries
        (LRU-evicted beyond it).
    spec_decode: speculative decoding (draft-propose / one-dispatch-
        verify): a proposer guesses spec_k tokens per live slot and the
        flagship verifies all spec_k+1 positions in ONE folded batched
        dispatch through the paged cache, applying its own per-position
        seeded sampler — accepted tokens are bit-identical to what
        non-speculative decode would have produced (greedy AND top-k;
        docs/performance.md round 20). Default OFF; None reads
        PADDLE_TPU_SPEC_DECODE (the kill switch — 1/true/on arms it).
        An armed engine additionally requires warmup() to pre-trace the
        verify program before any speculative dispatch runs, so a
        never-warmed engine is byte-identical to a spec-off one.
    spec_k: draft tokens proposed per slot per dispatch; None reads
        PADDLE_TPU_SPEC_K (default 4).
    spec_draft: 'ngram' (zero-weight prompt-lookup proposer — no second
        model) or a tiny GPT/Llama draft model instance sharing the
        tokenizer; None reads PADDLE_TPU_SPEC_DRAFT (default 'ngram').
    mem_ledger: device-memory ledger (observability.memledger): typed
        per-segment HBM attribution (kv_pages/prefix_sidecar/weights/
        ...), ground-truth cross-check with an unattributed residual,
        and headroom forecasting as engine_mem_* gauges. Default OFF;
        None reads PADDLE_TPU_MEM_LEDGER. A never-armed engine
        creates no ledger and registers no mem_* series (the profiler
        dormancy contract). Host-side accounting only: arming it
        leaves token streams and compile counts byte-identical.
    mem_admission: 'advisory' (would_fit consults are counters only)
        or 'hard' (submit() rejects a request whose full KV footprint
        would not fit the forecast headroom with a typed
        MemoryAdmissionError). None reads PADDLE_TPU_MEM_ADMISSION
        (default advisory). Hard mode needs a known capacity.
    mem_capacity_bytes: device-memory budget when the backend's
        memory_stats() has no bytes_limit (CPU, capped deployments);
        None reads PADDLE_TPU_MEM_CAPACITY_BYTES, else the ledger
        learns it from the device or runs capacity-blind.
    """

    def __init__(self, model, *, max_slots=8, page_size=16,
                 max_seq_len=256, num_pages=None, cache_dtype="float32",
                 use_flash=None, temperature=0.0, top_k=0, seed=0,
                 pad_token_id=0, steps_per_dispatch=8, donate=True,
                 admission_policy="wait", watchdog_timeout=None,
                 dispatch_retries=2, registry=None,
                 tenant_capacity=64, prefix_cache=None,
                 min_prefix_pages=None, prefix_max_entries=512,
                 spec_decode=None, spec_k=None, spec_draft=None,
                 profile=None, profile_hz=None, mem_ledger=None,
                 mem_admission=None, mem_capacity_bytes=None):
        if page_size % 8:
            raise ValueError(f"page_size must be a multiple of 8 "
                             f"(Mosaic sublane tiling), got {page_size}")
        model.eval()
        self.model = model
        cfg = model.config
        self.cfg = cfg
        self.kv_heads = (getattr(cfg, "num_key_value_heads", 0)
                         or cfg.num_attention_heads)
        self.groups = cfg.num_attention_heads // self.kv_heads
        self.num_layers = cfg.num_hidden_layers
        self.head_dim = cfg.head_dim
        self.page_size = int(page_size)
        self.max_slots = int(max_slots)
        self.max_pages_per_seq = -(-int(max_seq_len) // self.page_size)
        self.max_seq_len = self.max_pages_per_seq * self.page_size
        max_pos = getattr(cfg, "max_position_embeddings", None)
        if max_pos and self.max_seq_len > max_pos:
            raise ValueError(
                f"max_seq_len={max_seq_len} exceeds the model's "
                f"max_position_embeddings={max_pos}")
        if num_pages is None:
            num_pages = 1 + self.max_slots * self.max_pages_per_seq
        self.num_pages = int(num_pages)
        self.cache_dtype = str(cache_dtype)
        if self.cache_dtype not in ("float32", "bfloat16", "int8"):
            raise ValueError(f"cache_dtype {cache_dtype!r}: expected "
                             "float32 | bfloat16 | int8")
        from ..ops.attention import paged_flash_available
        self.use_flash = paged_flash_available(self.head_dim,
                                               self.page_size, use_flash)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.sampling_seed = int(seed)  # published in health() so the
        #                                 fleet capture archive records
        #                                 what replay must match for
        #                                 token-exact goldens
        self.pad_token_id = int(pad_token_id)
        self.steps_per_dispatch = int(steps_per_dispatch)
        self.donate = bool(donate)
        if prefix_cache is None:
            prefix_cache = os.environ.get(
                "PADDLE_TPU_PREFIX_CACHE", "1").lower() \
                not in ("0", "false", "off")
        if min_prefix_pages is None:
            min_prefix_pages = int(os.environ.get(
                "PADDLE_TPU_PREFIX_MIN_PAGES", "1"))
        self.prefix = PrefixIndex(
            self.page_size, min_pages=min_prefix_pages,
            max_entries=prefix_max_entries) if prefix_cache else None
        if spec_decode is None:
            spec_decode = os.environ.get(
                "PADDLE_TPU_SPEC_DECODE", "0").lower() \
                in ("1", "true", "on")
        if spec_k is None:
            spec_k = int(os.environ.get("PADDLE_TPU_SPEC_K", "4"))
        self.spec_k = int(spec_k)
        if self.spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        if spec_draft is None:
            spec_draft = os.environ.get("PADDLE_TPU_SPEC_DRAFT", "ngram")
        self.spec_draft = spec_draft
        if profile is None:
            profile = os.environ.get(
                "PADDLE_TPU_PROFILE", "0").lower() in ("1", "true", "on")
        self._profile_enabled = bool(profile)
        self._profile_hz = profile_hz
        from ..observability import memledger as _memledger
        if mem_ledger is None:
            mem_ledger = _memledger.mem_ledger_enabled_from_env()
        self._mem_enabled = bool(mem_ledger)
        self.mem_admission = (_memledger.mem_admission_from_env()
                              if mem_admission is None
                              else str(mem_admission))
        if self.mem_admission not in _memledger.ADMISSION_MODES:
            raise ValueError(
                f"mem_admission {mem_admission!r}: expected "
                f"{' | '.join(_memledger.ADMISSION_MODES)}")
        if mem_capacity_bytes is None:
            mem_capacity_bytes = _memledger.mem_capacity_from_env()
        self._mem_capacity_bytes = mem_capacity_bytes

        self._params, self._buffers = model.raw_state()
        self._pages = [alloc_pages(self.num_pages, self.page_size,
                                   self.kv_heads, self.head_dim,
                                   self.cache_dtype)
                       for _ in range(self.num_layers)]
        self._quantized = self.cache_dtype == "int8"

        b = self.max_slots
        self._page_table = np.zeros((b, self.max_pages_per_seq), np.int32)
        self._seq_lens = np.zeros((b,), np.int32)
        self._last_tokens = np.zeros((b,), np.int32)
        self._emitted = np.zeros((b,), np.int32)
        self._max_new = np.ones((b,), np.int32)
        self._eos = np.full((b,), -1, np.int32)  # -1 = no eos for slot
        self._done = np.ones((b,), bool)
        self._active = np.zeros((b,), bool)
        self._rng = jax.random.PRNGKey(seed)
        # prime the eager split executable NOW (result discarded, RNG
        # state untouched): the per-admission split below must never
        # pay its one-time process-wide compile inside a request's
        # TTFT — the replay latency baselines treat admission as
        # microseconds of host work
        jax.random.split(self._rng)
        # per-slot sampling key base: one fresh split per ADMISSION,
        # folded with the token's emitted index inside the programs
        # (key = fold_in(base, index)). Token streams are therefore a
        # pure function of (request, admission order, index) — not of
        # how decode work is scheduled into dispatches — which is what
        # lets speculative verify reproduce non-speculative sampling
        # bit-for-bit at any acceptance pattern.
        self._key_base = np.zeros((b, 2), np.uint32)

        # device-resident mirror of the scheduling arrays: refreshed
        # from host only when admission/eviction mutates them, so a
        # steady full-pool decode pays zero host->device uploads per
        # dispatch (the compiled step's launch overhead is the serving
        # metric's denominator)
        self._dev_sched = None

        self._free_pages = list(range(1, self.num_pages))  # 0 = trash
        self._slots = [None] * b
        self._queue = collections.deque()
        self._finished = []
        self._next_rid = 0

        # -- resilience/degradation state (all host-side: deadlines,
        # cancellation, admission policy and the watchdog never touch
        # the compiled programs, so zero-recompile survives chaos)
        if admission_policy not in ("wait", "reject", "evict"):
            raise ValueError(f"admission_policy {admission_policy!r}: "
                             "expected wait | reject | evict")
        self.admission_policy = admission_policy
        self.dispatch_retries = int(dispatch_retries)
        from ..resilience.retry import RetryStats
        self.retry_stats = RetryStats()
        self._watchdog = None
        if watchdog_timeout is not None:
            from ..resilience.watchdog import Watchdog
            self._watchdog = Watchdog(timeout_s=watchdog_timeout).start()
        self._rounds = 0
        self._admit_seq = 0
        self._cancel_pending = set()
        self.last_dispatch_s = 0.0
        # lifecycle: serving -> (draining <-> serving) -> closed. A
        # router/LB reads this through health()["state"] to tell
        # "busy" from "going away" (docs/robustness.md fleet section)
        self._state = "serving"

        # -- observability: every counter the engine keeps lives in the
        # registry (status_counts/health() are snapshot VIEWS of it),
        # so reset_counters() has exactly one reset semantic. Default
        # is a private registry: series like serve_requests_total are
        # identified by name alone, so sharing the process-global one
        # between engines would alias their counters (and reset would
        # zero a sibling engine's measurement window)
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        reg = self.registry
        self._own_series = []

        def own(m):
            self._own_series.append(m)
            return m
        self._m_queue_wait = own(reg.histogram(
            "serve_queue_wait_seconds",
            help="submit -> admission (prefill start) wait"))
        self._m_ttft = own(reg.histogram(
            "serve_ttft_seconds",
            help="submit -> first generated token (incl. queue wait "
                 "and prefill)"))
        self._m_tok = own(reg.histogram(
            "serve_decode_token_seconds",
            help="per-token batched-decode latency (dispatch wall / "
                 "tokens, count-weighted)"))
        self._m_dispatch = own(reg.histogram(
            "serve_dispatch_seconds",
            help="batched decode dispatch wall time"))
        self._m_decode_tokens = own(reg.counter(
            "serve_decode_tokens_total",
            help="tokens generated by batched decode"))
        self._m_decode_dispatches = own(reg.counter(
            "serve_decode_dispatches_total",
            help="batched decode dispatches"))
        self._m_deadline = own(reg.counter(
            "serve_deadline_misses_total",
            help="requests finished with status=expired"))
        self._m_evictions = own(reg.counter(
            "serve_evictions_total",
            help="running requests preempted by the evict admission "
                 "policy"))
        self._m_retries = own(reg.counter(
            "serve_dispatch_retries_total",
            help="transient dispatch errors absorbed by the retry "
                 "wrapper"))
        self._m_wedges = own(reg.counter(
            "serve_watchdog_wedges_total",
            help="dispatches the watchdog flagged past its timeout"))
        self._g_free_pages = own(reg.gauge(
            "serve_free_pages", help="KV pages on the free list"))
        self._g_occupancy = own(reg.gauge(
            "serve_page_occupancy",
            help="fraction of usable KV pages in use"))
        self._g_queue_depth = own(reg.gauge(
            "serve_queue_depth", help="requests awaiting admission"))
        self._g_running = own(reg.gauge(
            "serve_running", help="requests occupying a slot"))
        self._g_prefix_occ = own(reg.gauge(
            "prefix_cache_occupancy",
            help="fraction of usable KV pages owned by the shared "
                 "prefix index (0 when the cache is off/empty)"))
        self._m_req = {}            # status -> serve_requests_total
        for status in ("ok", "expired", "cancelled", "rejected",
                       "evicted"):
            self._status_counter(status)
        # per-tenant usage attribution (observability.tenancy): a
        # bounded space-saving sketch of tokens in/out, queue-wait and
        # KV-page-seconds for tenant-tagged requests. Host-side dict
        # arithmetic at the finish boundary the engine already owns —
        # zero-recompile untouched; untagged requests skip it entirely
        from ..observability.tenancy import TenantAccountant
        self.tenants = TenantAccountant(capacity=tenant_capacity,
                                        registry=reg)
        self._seen_retries = 0
        self._seen_wedges = 0
        # _sync_registry runs on the step() thread AND (via health())
        # on metrics-exporter HTTP threads — the diff-and-increment
        # must not race
        self._sync_lock = threading.Lock()
        self._update_gauges()

        # the trace counters ARE a RecompileTracer's (same dict): the
        # zero-recompile assertion's ground truth and the queryable
        # recompile report (observability.trace.report_all) share one
        # source of truth
        from ..observability.trace import RecompileTracer
        self.tracer = RecompileTracer(name="serving",
                                      registry=self.registry)
        # per-request span timeline (queue -> prefill -> decode
        # dispatches -> finish, with page/eviction instants) — a
        # bounded ring of host timestamps recorded at the step
        # boundaries the engine already owns; export via
        # observability.spans.export_chrome (docs/observability.md)
        from ..observability.spans import SpanRecorder
        self.spans = SpanRecorder(name="serving")
        # continuous host sampling profiler (observability.contprof):
        # armed via PADDLE_TPU_PROFILE / the profile ctor knob. A
        # never-armed engine creates NO profiler object at all — the
        # same dormancy contract prefix caching and spec decode keep,
        # so legacy goldens stay byte-identical. Host-side only:
        # profiling ON leaves compile counts frozen (chaos-asserted).
        self.profiler = None
        if self._profile_enabled:
            from ..observability.contprof import ContinuousProfiler
            self.profiler = ContinuousProfiler(
                hz=self._profile_hz, registry=reg,
                name="engine").start()
        # device-memory ledger (observability.memledger): armed via
        # PADDLE_TPU_MEM_LEDGER / the mem_ledger ctor knob, same
        # dormancy contract as the profiler — a never-armed engine
        # creates NO ledger and registers NO mem_* series. track/
        # release are host dict arithmetic; the ground-truth sweep
        # runs at health() cadence, never the dispatch hot path.
        self.ledger = None
        # per-page KV bytes (all layers, incl. int8 scale sidecars):
        # the unit the admission hint prices a request in. Host attr
        # walk over pool metadata, computed once.
        self._page_bytes = (_memledger.nbytes_of(self._pages)
                            // max(self.num_pages, 1))
        if self._mem_enabled:
            self.ledger = _memledger.MemoryLedger(
                registry=reg, name="engine",
                capacity_bytes=self._mem_capacity_bytes)
            model_tag = type(model).__name__
            self.ledger.track("weights", (self._params, self._buffers),
                              label=f"model={model_tag}")
            self.ledger.track(
                "kv_pages", self._pages,
                label=f"dtype={self.cache_dtype},model={model_tag}")
            self.ledger.add_audit(self._mem_audit)
        self._exporter = None
        self._trace_counts = self.tracer._counts
        # AOT export surface: every compiled serving program's RAW
        # (pre-tracer) body + jit kwargs, recorded by _counting as the
        # program is built. jit.serving_artifact lowers these through
        # jax.export so a respawned replica can boot from serialized
        # StableHLO instead of re-tracing Python (docs/robustness.md
        # "Artifact boot").
        self._aot_programs = {}
        # how THIS engine became serving-ready: "traced" (warmup) or
        # "aot" (artifact load). serving_artifact.warm_boot stamps
        # mode/boot_s/artifact; heartbeats carry it to fleet_top's
        # BOOT column.
        self.boot_info = {"mode": "traced", "boot_s": None,
                          "artifact": None}
        self._decode_fn = self._build_decode_fn()
        self._prefill_fns = {}
        self._tail_prefill_fns = {}
        # warm-boot bookkeeping (warmup()): which prefill buckets and
        # whether the decode program were pre-traced at boot. Tail
        # buckets gate the prefix-cache HIT path: a hit admission only
        # happens when its tail program is already traced, so caching
        # can never introduce a mid-traffic compile
        self._warmed_buckets = set()
        self._warmed_tail_buckets = set()
        self._warmed_decode = False
        # speculative decoding: proposer + folded verify program.
        # Dispatch routing is gated on _warmed_spec (set by warmup()),
        # mirroring the prefix-cache tail-bucket gate: an armed-but-
        # never-warmed engine takes the plain decode path for every
        # dispatch, so speculation can never introduce a mid-traffic
        # compile and a never-warmed engine is byte-identical to a
        # spec-off one
        self._spec = None
        self._spec_verify_fn = None
        self._warmed_spec = False
        if spec_decode:
            from .speculative import make_proposer
            self._spec = make_proposer(self, self.spec_draft)
            self._spec_verify_fn = self._build_spec_verify_fn()
            self._m_spec_proposed = own(reg.counter(
                "serve_spec_proposed_total",
                help="draft tokens dispatched to speculative verify"))
            self._m_spec_accepted = own(reg.counter(
                "serve_spec_accepted_total",
                help="draft tokens the target model confirmed "
                     "(committed bit-identical to plain decode)"))
            self._m_spec_dispatches = own(reg.counter(
                "serve_spec_dispatches_total",
                help="folded verify dispatches (each commits >= 1 "
                     "token per live slot)"))
        # decode-dispatch accounting: batched-decode throughput is THE
        # serving metric (wall time also pays per-request prefill,
        # which is batch-1 by construction); bench.py --serve reads
        # these for the ladder's tok/s rows
        self.decode_seconds = 0.0
        self.decode_tokens = 0
        self.decode_dispatches = 0

    def _status_counter(self, status):
        c = self._m_req.get(status)
        if c is None:
            c = self.registry.counter(
                "serve_requests_total",
                help="finished requests by terminal status",
                labels={"status": status})
            self._own_series.append(c)
            self._m_req[status] = c
        return c

    @property
    def status_counts(self):
        """Snapshot view of serve_requests_total{status=...}."""
        return {s: int(c.value) for s, c in self._m_req.items()}

    def _update_gauges(self):
        self._g_free_pages.set(len(self._free_pages))
        usable = max(self.num_pages - 1, 1)
        self._g_occupancy.set(
            round(1.0 - len(self._free_pages) / usable, 6))
        self._g_queue_depth.set(len(self._queue))
        self._g_running.set(
            sum(1 for s in self._slots if s is not None))
        if self.prefix is not None:
            self._g_prefix_occ.set(
                round(self.prefix.owned_page_count / usable, 6))

    def _sync_registry(self):
        """Fold the monotonic retry/watchdog sources into registry
        counters (diffed, so a registry reset restarts them at 0 —
        the uniform-reset semantics health() reports through).

        Locked: health() runs this from the metrics exporter's HTTP
        threads too (serve_metrics), and the _seen_* read-modify-write
        racing the step() thread would double-count a wedge/retry —
        and double-dump the wedge flight record."""
        with self._sync_lock:
            r = self.retry_stats.retries
            if r > self._seen_retries:
                self._m_retries.inc(r - self._seen_retries)
            self._seen_retries = r
            if self._watchdog is not None:
                w = self._watchdog.wedge_count
                if w > self._seen_wedges:
                    self._m_wedges.inc(w - self._seen_wedges)
                    # a wedged dispatch is a flight-recorder trigger:
                    # the recent dispatch/request ring + which op
                    # wedged
                    from ..observability import flightrec
                    flightrec.dump("wedge", extra={
                        "op": self._watchdog.last_wedge_op,
                        "wedge_count": int(w), "round": self._rounds})
                self._seen_wedges = w
            self._update_gauges()

    def reset_counters(self):
        """Zero EVERY serve counter uniformly: decode throughput, the
        per-status request totals, latency histograms, and the retry/
        watchdog counts (which previously survived a reset and made
        health() diverge from the window being measured)."""
        self.decode_seconds = 0.0
        self.decode_tokens = 0
        self.decode_dispatches = 0
        self._sync_registry()     # consume pending source increments
        for m in self._own_series:
            m.reset()
        self._update_gauges()     # gauges reflect live state, not 0

    # -- public API ---------------------------------------------------------

    def submit(self, prompt, max_new_tokens=16, eos_token_id=None,
               deadline_ms=None, priority=0, trace=None, tenant=None):
        """Queue one request; returns its id. Admitted at the next
        step() boundary (slot + pages permitting).

        deadline_ms: wall budget from NOW for the whole request
            (queueing + prefill + decode). Expiry is detected at host
            step boundaries; the request finishes with
            status='expired' and whatever tokens it produced.
        priority: larger = more important (evict admission policy).
        trace: distributed-trace context (observability.dtrace wire
            form, minted by a FleetRouter and propagated through the
            replica transport). The engine then records this
            request's queue/prefill/decode legs as child spans in the
            process-global trace store — pure host-side dict appends
            at the step boundaries the engine already owns, so the
            zero-recompile contract is untouched. None (the default)
            records nothing.
        tenant: usage-attribution label (observability.tenancy,
            threaded from ``FleetRouter.submit`` through the replica
            transports). Tagged requests accumulate tokens in/out,
            queue-wait and KV-page-seconds into ``engine.tenants``
            and stamp them on their result; None (the default) skips
            accounting entirely."""
        if self._state != "serving":
            if self._state == "closed":
                raise RuntimeError("ServingEngine is closed")
            raise RuntimeError(
                "ServingEngine is draining (not admitting); resume() "
                "re-opens admission")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if not len(prompt):
            raise ValueError("empty prompt")
        need = len(prompt) + int(max_new_tokens)
        if need > self.max_seq_len:
            raise ValueError(
                f"prompt({len(prompt)}) + max_new_tokens"
                f"({max_new_tokens}) = {need} exceeds max_seq_len="
                f"{self.max_seq_len}")
        need_pages = -(-need // self.page_size)
        if need_pages > self.num_pages - 1:
            # would otherwise sit in the admission queue FOREVER:
            # back-pressure can free at most the whole pool (page 0 is
            # reserved), so this request can never be admitted
            raise ValueError(
                f"request needs {need_pages} KV pages (prompt "
                f"{len(prompt)} + {int(max_new_tokens)} new tokens @ "
                f"page_size={self.page_size}) but the pool only has "
                f"{self.num_pages - 1} usable — it would wedge the "
                "admission queue. Raise num_pages or shorten the "
                "request.")
        if self.ledger is not None and self.mem_admission == "hard":
            # hard admission (PADDLE_TPU_MEM_ADMISSION=hard): reject
            # a request whose full KV footprint would not fit the
            # forecast headroom with a typed error NOW, instead of
            # OOMing mid-decode. Conservative by design — judged
            # against current headroom, not what draining requests
            # may free (a kill switch, not a scheduler).
            need_bytes = need_pages * self._page_bytes
            if self.ledger.admission_check(need_bytes) is False:
                from ..observability.memledger import \
                    MemoryAdmissionError
                raise MemoryAdmissionError(
                    need_bytes, self.ledger.headroom_bytes(),
                    self.ledger.capacity_bytes)
        deadline = None if deadline_ms is None \
            else time.monotonic() + float(deadline_ms) / 1e3
        rid = self._next_rid
        self._next_rid += 1
        req = ServeRequest(rid, prompt, max_new_tokens,
                           eos_token_id, deadline=deadline,
                           priority=priority, trace=trace,
                           tenant=tenant)
        if self.prefix is not None:
            # rolling page-boundary fingerprints, once per request —
            # a failover continuation re-submitted here re-fingerprints
            # naturally (hit = cheap re-admission, miss = normal
            # continuation prefill)
            req.prefix_fps = prefix_fingerprints(prompt, self.page_size)
        self._queue.append(req)
        return rid

    @staticmethod
    def _dtrace_add(ctx, name, t0, t1=None, args=None, outcome=None):
        """Record one distributed-trace child span (no-op for
        untraced requests; never raises — tracing must not kill a
        step)."""
        if ctx is None:
            return
        try:
            from ..observability import dtrace
            dtrace.get_store().add_span(ctx, name, t0, t1, args=args,
                                        outcome=outcome)
        except Exception:  # noqa: BLE001 — accounting only
            pass

    def cancel(self, rid):
        """Request cancellation of a queued or running request. Takes
        effect at the next step() boundary (never mid-dispatch — a
        compiled decode program is never interrupted): the request
        finishes with status='cancelled' and its partial tokens.
        Returns True when `rid` is still queued or running, False when
        unknown or already finished."""
        if any(r.rid == rid for r in self._queue) or any(
                s is not None and s.req.rid == rid for s in self._slots):
            self._cancel_pending.add(rid)
            return True
        return False

    def step(self):
        """One scheduling round: apply cancellations and deadline
        expiry, evict finished slots, admit queued requests (per the
        admission policy), run ONE batched decode dispatch
        (steps_per_dispatch tokens x all live slots). Returns the list
        of requests finished this round as dicts
        {id, prompt, tokens, status} (tokens = generated only).

        An unhandled exception here is a flight-recorder trigger: the
        ring of recent dispatch/request records dumps to
        flight_serve_exception.json before the error propagates."""
        if self._state == "closed":
            raise RuntimeError("ServingEngine is closed")
        try:
            return self._step_impl()
        except Exception as e:
            from ..observability import flightrec
            flightrec.dump("serve_exception",
                           extra={"error": f"{type(e).__name__}: {e}",
                                  "round": self._rounds})
            raise

    def _step_impl(self):
        self._rounds += 1
        if self._state == "draining":
            # draining: nothing new admits, and anything still QUEUED
            # resolves as cancelled NOW (a router re-places it on a
            # healthy replica); in-flight slots keep decoding below
            # until they finish token-exactly
            while self._queue:
                self._finish_request(self._queue.popleft(), "cancelled")
        self._apply_cancels()
        self._expire_deadlines()
        self._evict()
        if self._state == "serving":
            self._admit()
        if self._active.any() and not (self._done | ~self._active).all():
            if self._spec is not None and self._warmed_spec:
                self._dispatch_spec()
            else:
                self._dispatch_decode()
        self._evict()
        self._sync_registry()
        out, self._finished = self._finished, []
        return out

    def run_to_completion(self, max_rounds=10_000):
        """Drive step() until queue and slots drain; returns all
        finished requests in completion order."""
        results = []
        rounds = 0
        while self._queue or any(s is not None for s in self._slots):
            results.extend(self.step())
            rounds += 1
            if rounds > max_rounds:
                raise RuntimeError("serving loop did not drain "
                                   f"within {max_rounds} rounds")
        return results

    def generate(self, prompts, max_new_tokens=16, eos_token_id=None):
        """Convenience batch API: submit all, drain, return generated
        token lists in submission order."""
        ids = [self.submit(p, max_new_tokens, eos_token_id)
               for p in prompts]
        res = {r["id"]: r for r in self.run_to_completion()}
        return [res[i]["tokens"] for i in ids]

    def compile_counts(self):
        """Trace counts per compiled program (name -> count). Steady
        state == this dict stops changing; bench.py --serve asserts
        it per ladder rung."""
        return dict(self._trace_counts)

    @property
    def free_page_count(self):
        return len(self._free_pages)

    @property
    def state(self):
        """Lifecycle state: 'serving' | 'draining' | 'closed'. Also in
        health()/'/healthz' so an external LB can tell a busy replica
        from one that is going away."""
        return self._state

    @property
    def idle(self):
        """True when nothing is queued and no slot is occupied — the
        'drain complete' condition a replica worker polls."""
        return not self._queue and all(s is None for s in self._slots)

    def drain(self):
        """Stop admitting (graceful shutdown / preemption notice):
        queued requests resolve as status='cancelled' at the next
        step() boundary so a router can re-place them, while in-flight
        requests keep decoding to their normal finish, token-exactly.
        Idempotent; submit() during the drain raises. resume()
        re-opens admission (rejoin), close() retires the engine."""
        if self._state == "closed":
            raise RuntimeError("ServingEngine is closed")
        self._state = "draining"

    def resume(self):
        """Re-open admission after drain() (fleet rejoin). The engine
        keeps its compiled programs, so a drain/rejoin cycle costs
        zero recompiles."""
        if self._state == "closed":
            raise RuntimeError("ServingEngine is closed")
        self._state = "serving"

    def drain_to_completion(self, max_rounds=10_000):
        """drain(), then step() until every slot finishes; returns the
        finished-request dicts (in-flight complete token-exactly,
        queued come back cancelled). Bounded by max_rounds — the drain
        path never wedges."""
        self.drain()
        results = []
        rounds = 0
        while not self.idle:
            results.extend(self.step())
            rounds += 1
            if rounds > max_rounds:
                raise RuntimeError("drain did not complete within "
                                   f"{max_rounds} rounds")
        return results

    def _bucket_for(self, n):
        """The pow2, whole-page prefill bucket a prompt of length `n`
        lands in (the _admit_one formula, shared with warmup)."""
        ps = self.page_size
        bucket = min(max(_next_pow2(int(n)), ps), self.max_seq_len)
        return min(-(-bucket // ps) * ps, self.max_seq_len)

    def warmup(self, buckets=(), decode=True):
        """Pre-trace the serving programs BEFORE traffic: one prefill
        program per bucket plus the batched decode scan, driven with
        synthetic inputs whose shapes/dtypes are exactly what real
        admission passes — so the first real wave of those buckets
        compiles NOTHING. The traces count once, here, in the boot
        compile budget (`compile_counts()` shows them like any other
        trace); this is also the fix for the first-request TTFT cliff
        in single-replica serving (the first admission used to pay the
        prefill compile inside a request's latency), and the warm-boot
        contract a respawned fleet replica re-enters rotation under
        (serving-ready, frozen counts — docs/robustness.md "Process
        supervision").

        buckets: prompt lengths OR bucket sizes — each is normalized
            through the same pow2/whole-page formula admission uses,
            then traced once (already-warm buckets are skipped).
        decode: also trace the batched decode program (default True).

        Writes land exclusively in the reserved trash page (the
        synthetic page tables point every page there) and the sampling
        RNG state is NOT advanced, so a warmed engine generates
        token-for-token what an unwarmed one would. Requires an idle
        engine (warmup is a boot step, not a mid-traffic one).
        Returns the sorted list of buckets warmed by THIS call."""
        if self._state == "closed":
            raise RuntimeError("ServingEngine is closed")
        if not self.idle:
            raise RuntimeError("warmup() needs an idle engine — it is "
                               "a boot step, not a mid-traffic one")
        warmed = []
        norm = sorted({self._bucket_for(n) for n in buckets})
        for n in norm:
            if n in self._warmed_buckets:
                continue
            # the pool is donated to the program and the returned
            # buffers adopted (contents untouched outside the trash
            # page); the RNG rides along as a synthetic key only —
            # host state is NOT advanced (see docstring)
            self._prime(f"prefill_{n}", self._prefill_fn(n))
            self._warmed_buckets.add(n)
            warmed.append(n)
        if self.prefix is not None and norm:
            # tail-prefill ladder: a prefix HIT on a prompt of bucket n
            # runs a tail of 1..n tokens, whose bucket is one of the
            # pow2/whole-page values below n — trace them all now so a
            # hit never compiles mid-traffic (the hit path is gated on
            # exactly this set)
            tails = set()
            for n in norm:
                tails.update(self._bucket_for(t)
                             for t in range(1, n + 1))
            for t in sorted(tails):
                if t in self._warmed_tail_buckets:
                    continue
                self._prime(f"tail_prefill_{t}",
                            self._tail_prefill_fn(t))
                self._warmed_tail_buckets.add(t)
            self._warm_eager_ladder(norm)
        if decode and not self._warmed_decode:
            self._prime("decode", self._decode_fn)
            self._warmed_decode = True
        if self._spec is not None and decode:
            # speculative programs: the folded verify (all-trash table,
            # inactive slots — writes land in the trash page) plus the
            # proposer's own programs (draft prefill per warmed bucket
            # + the propose scan for a model draft; nothing for ngram).
            # _warmed_spec is the arming gate: until it flips, every
            # dispatch takes the plain decode path
            if not self._warmed_spec:
                self._prime("spec_verify", self._spec_verify_fn)
                self._warmed_spec = True
            self._spec.warmup(self, norm)
        from ..observability import flightrec
        flightrec.note("serve_warmup", buckets=warmed,
                       tail_buckets=sorted(self._warmed_tail_buckets),
                       decode=self._warmed_decode,
                       spec=self._warmed_spec)
        return warmed

    def _warm_args(self, name):
        """Synthetic boot-time arguments for serving program `name` —
        shapes and dtypes exactly what real dispatch passes, page
        tables pointing every write at the reserved trash page, the
        RNG riding along as a value only (host state not advanced).
        ONE builder shared by warmup() (tracing boot) and
        jit.serving_artifact (AOT export signatures + load-time
        priming), so the two boot paths can never drift apart."""
        if name == "decode":
            b = self.max_slots
            sched = (np.full((b, self.max_pages_per_seq), TRASH_PAGE,
                             np.int32),
                     np.zeros((b,), np.int32),      # seq_lens
                     np.zeros((b,), np.int32),      # last_tokens
                     np.zeros((b,), bool),          # active: none
                     np.ones((b,), bool),           # done: all
                     np.zeros((b,), np.int32),      # emitted
                     np.ones((b,), np.int32),       # max_new
                     np.full((b,), -1, np.int32),   # eos
                     np.zeros((b, 2), np.uint32))   # key_base
            return (self._params, self._buffers, self._pages,
                    *(jnp.asarray(a) for a in sched))
        if name == "spec_verify":
            b = self.max_slots
            return (self._params, self._buffers, self._pages,
                    jnp.asarray(np.full((b, self.max_pages_per_seq),
                                        TRASH_PAGE, np.int32)),
                    jnp.asarray(np.zeros((b,), np.int32)),
                    jnp.asarray(np.zeros((b,), np.int32)),
                    jnp.asarray(np.zeros((b, self.spec_k), np.int32)),
                    jnp.asarray(np.zeros((b, 2), np.uint32)),
                    jnp.asarray(np.zeros((b,), np.int32)))
        if name.startswith("tail_prefill_"):
            t = int(name.rsplit("_", 1)[1])
            pre = self.max_seq_len
            zero = jnp.zeros((1, pre, self.kv_heads, self.head_dim),
                             jnp.float32)
            ids = np.full((1, t), self.pad_token_id, np.int32)
            pages_vec = np.full((t // self.page_size,), TRASH_PAGE,
                                np.int32)
            return (self._params, self._buffers, self._pages,
                    [zero] * self.num_layers, [zero] * self.num_layers,
                    jnp.asarray(ids), jnp.int32(0), jnp.int32(1),
                    jnp.asarray(pages_vec), self._rng)
        if name.startswith("prefill_"):
            n = int(name.rsplit("_", 1)[1])
            ids = np.full((1, n), self.pad_token_id, np.int32)
            pages_vec = np.full((n // self.page_size,), TRASH_PAGE,
                                np.int32)
            return (self._params, self._buffers, self._pages,
                    jnp.asarray(ids), jnp.int32(1),
                    jnp.asarray(pages_vec), self._rng)
        raise ValueError(f"unknown serving program {name!r}")

    def _prime(self, name, fn):
        """Run `fn` once with _warm_args(name) and adopt the returned
        page pool (the pool is donated in; every serving program
        returns its new pages at result index 1). Writes land only in
        the trash page and the RNG is not advanced, so a primed engine
        generates token-for-token what an unprimed one would."""
        out = fn(*self._warm_args(name))
        self._pages = out[1]

    def _warm_eager_ladder(self, norm):
        """Pre-run the prefix-REGISTRATION path's eager ops: jnp.pad
        at full prefill (bucket -> max_seq_len sidecar) and the
        extension splice at a hit are eager XLA ops whose executables
        key on shapes only (splice starts are dynamic operands) — run
        every shape combo the warmed buckets can produce so a
        registering wave never pays a backend compile mid-traffic."""
        pre = self.max_seq_len
        zero = jnp.zeros((1, pre, self.kv_heads, self.head_dim),
                         jnp.float32)
        for n in norm:
            if n < pre:
                jnp.pad(zero[:, :n],
                        ((0, 0), (0, pre - n), (0, 0), (0, 0)))
        for t in sorted(self._warmed_tail_buckets):
            src = zero[:, :t]
            for w in sorted({min(t, pre - jj * self.page_size)
                             for jj in range(1, pre //
                                             self.page_size)}):
                jax.lax.dynamic_update_slice(
                    zero, src if w == t else src[:, :w],
                    (0, 0, 0, 0))

    def _install_aot_program(self, name, call):
        """Install a pre-compiled (jax.export-restored) serving
        program under site `name`, replacing the build-on-first-use
        traced one. The caller (jit.serving_artifact.load_artifact)
        owns priming it and flipping the matching _warmed_* flag —
        installation alone must not claim warmth."""
        if name == "decode":
            self._decode_fn = call
        elif name == "spec_verify":
            if self._spec is None:
                raise ValueError(
                    "spec_verify program on a spec-off engine")
            self._spec_verify_fn = call
        elif name.startswith("tail_prefill_"):
            self._tail_prefill_fns[int(name.rsplit("_", 1)[1])] = call
        elif name.startswith("prefill_"):
            self._prefill_fns[int(name.rsplit("_", 1)[1])] = call
        else:
            raise ValueError(f"unknown serving program {name!r}")

    @property
    def warmed(self):
        """True once the batched decode program has been traced — by
        warmup() or by real traffic (a rejoined engine that already
        served is warm: its compiled programs carried over). The
        supervisor's boot gate reads this off the heartbeat;
        per-bucket detail in health()."""
        return self._warmed_decode \
            or bool(self._trace_counts.get("decode"))

    def export_inflight(self):
        """Host-side snapshot of every unfinished request: in-flight
        slots with their partial tokens (queued=False) and
        still-queued requests (queued=True, no tokens). The fleet
        failover path reads this off a dead/wedged replica to
        continuation-resubmit elsewhere with the completed prefix
        deduped; in a subprocess deployment the same facts arrive over
        the streaming token channel. Pure bookkeeping — no device
        sync, no compilation."""
        out = []
        for slot in self._slots:
            if slot is None:
                continue
            r = slot.req
            out.append({"rid": r.rid, "prompt": r.prompt.tolist(),
                        "tokens": list(slot.out_tokens),
                        "max_new_tokens": r.max_new_tokens,
                        "eos_token_id": r.eos_token_id,
                        "priority": r.priority, "queued": False})
        for r in self._queue:
            out.append({"rid": r.rid, "prompt": r.prompt.tolist(),
                        "tokens": [],
                        "max_new_tokens": r.max_new_tokens,
                        "eos_token_id": r.eos_token_id,
                        "priority": r.priority, "queued": True})
        return out

    def serve_metrics(self, port=0, host="127.0.0.1"):
        """Attach a live HTTP exporter to THIS engine: /metrics is the
        engine's registry, /healthz is health(), /report the
        recompile + cost reports. Returns the exporter (read .port
        when port=0); close() (and engine close()) shuts it down. A
        second call replaces the first."""
        from ..observability.exporter import MetricsExporter
        if self._exporter is not None:
            self._exporter.close()
        profile_fn = None
        if self.profiler is not None:
            profile_fn = lambda window: \
                self.profiler.report(window_s=window)  # noqa: E731

        def memory_fn(window):
            # /memory is always routable on an engine exporter: an
            # unarmed ledger answers a stub (HTTP 200) telling the
            # scraper how to arm it, instead of a route-shaped 404
            if self.ledger is not None:
                return self.ledger.report(window_s=window)
            return {"armed": False,
                    "note": "no ledger armed "
                            "(PADDLE_TPU_MEM_LEDGER=1 or "
                            "mem_ledger=True)"}
        self._exporter = MetricsExporter(
            registry=self.registry, port=port, host=host,
            health_fn=self.health,
            # span-ring overflow is never silent: the /report doc
            # carries each recorder's eviction count
            report_fn=lambda: {"spans_evicted": {
                self.spans.name: int(self.spans.evicted)}},
            tenants_fn=self.tenants.report,
            profile_fn=profile_fn,
            memory_fn=memory_fn)
        return self._exporter

    def close(self):
        """Retire the engine: every queued request resolves as
        status='cancelled', every running one finishes with its
        partial tokens as 'cancelled', ALL pages return to the free
        list, then host-side resources are released (the watchdog's
        polling thread, the metrics exporter's port + thread, the
        tracer's slot in the process-wide report set). Idempotent, and
        composes with the drain path: drain_to_completion() then
        close() is the graceful shutdown; a bare close() is the
        impatient one — neither wedges. Returns the finished-request
        dicts resolved by the close (cancelled work keeps its partial
        tokens) plus any earlier results not yet collected — step()
        raises after close, so this is the last chance to read them.
        After close(), submit()/step() raise
        RuntimeError('ServingEngine is closed'). Compiled programs and
        the page pool are plain GC'd objects."""
        if self._state == "closed":
            return []
        while self._queue:
            self._finish_request(self._queue.popleft(), "cancelled")
        for b in range(self.max_slots):
            if self._slots[b] is not None:
                # a done-but-unswept slot keeps its natural status;
                # live ones are cancelled with their partial tokens
                self._finish_slot(
                    b, None if self._done[b] else "cancelled")
        if self.prefix is not None:
            # every slot is gone, so nothing is pinned: a full evict
            # returns the index-owned pages and keeps the close()
            # contract (ALL pages back on the free list)
            self._free_pages.extend(self.prefix.evict(self.num_pages))
        self._state = "closed"
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None
        if self._exporter is not None:
            self._exporter.close()
            self._exporter = None
        if self.profiler is not None:
            self.profiler.stop()
        if self.ledger is not None:
            self.ledger.close()
        self.tracer.close()
        out, self._finished = self._finished, []
        return out

    def __del__(self):
        wd = getattr(self, "_watchdog", None)
        if wd is not None:
            # signal only — joining a thread from a finalizer can
            # deadlock interpreter shutdown
            wd._stop.set()
        ex = getattr(self, "_exporter", None)
        if ex is not None:
            try:
                ex.close()
            except Exception:  # noqa: BLE001 — finalizer safety
                pass
        pr = getattr(self, "profiler", None)
        if pr is not None:
            # signal only (the _watchdog convention): joining the
            # sampler thread from a finalizer can deadlock shutdown
            pr._stop.set()
        tr = getattr(self, "tracer", None)
        if tr is not None:
            # an engine retired without close() must not pin a live
            # tracer in the process-wide report set forever
            tr.close()

    def health(self):
        """One host-side snapshot of engine liveness and degradation
        state — the thing a load balancer or operator pages on. Pure
        bookkeeping reads: no device sync, no compilation. Counter
        fields are views of the registry's serve_* series, so this and
        metrics.json can never disagree and reset_counters() resets
        both at once."""
        self._sync_registry()
        running = sum(1 for s in self._slots if s is not None)
        now = time.monotonic()
        h = {"state": self._state,
             "running": running,
             "queued": len(self._queue),
             "oldest_queued_s": round(
                 max((now - r.submitted_at for r in self._queue),
                     default=0.0), 6),
             "free_pages": len(self._free_pages),
             "total_pages": self.num_pages - 1,
             "page_occupancy": self._g_occupancy.value,
             "rounds": self._rounds,
             "decode_dispatches": self.decode_dispatches,
             "decode_tokens": self.decode_tokens,
             "last_dispatch_s": round(self.last_dispatch_s, 6),
             "results_pending": len(self._finished),
             "cancels_pending": len(self._cancel_pending),
             "admission_policy": self.admission_policy,
             "dispatch_retries": int(self._m_retries.value),
             "deadline_misses": int(self._m_deadline.value),
             "evictions": int(self._m_evictions.value),
             "status_counts": dict(self.status_counts),
             "warmed": self.warmed,
             "warmed_buckets": sorted(self._warmed_buckets),
             # how this engine became serving-ready: traced warmup or
             # an AOT artifact load (fleet_top's BOOT column)
             "boot": dict(self.boot_info),
             "tenants_tracked": self.tenants.tracked,
             # the decode-determinism fingerprint: replayed traffic is
             # token-exact only when these (and the weights) match —
             # the traffic-capture plane archives them per replica
             "sampling": {"temperature": self.temperature,
                          "top_k": self.top_k,
                          "seed": self.sampling_seed},
             "compile_counts": self.compile_counts()}
        if self.prefix is not None:
            st = self.prefix.stats()
            st["occupancy"] = self._g_prefix_occ.value
            st["min_pages"] = self.prefix.min_pages
            st["page_size"] = self.page_size
            st["top"] = [{"fp": f, "pages": p, "hits": n}
                         for f, p, n in self.prefix.top_fingerprints()]
            # the full boundary inventory: the fleet router harvests
            # this off heartbeats for prefix-affinity placement
            st["fingerprints"] = sorted(self.prefix.fingerprint_set())
            h["prefix_cache"] = st
        if self._spec is not None:
            # the fleet router delta-folds proposed/accepted/dispatches
            # off heartbeats into fleet_spec_* (acceptance canary)
            prop = int(self._m_spec_proposed.value)
            acc = int(self._m_spec_accepted.value)
            h["spec"] = {"k": self.spec_k,
                         "draft": self._spec.kind,
                         "armed": self._warmed_spec,
                         "proposed": prop,
                         "accepted": acc,
                         "dispatches":
                             int(self._m_spec_dispatches.value),
                         "acceptance_rate":
                             round(acc / prop, 6) if prop else None}
        if self.profiler is not None:
            # bounded per-phase hotspot digest riding the heartbeat:
            # the fleet router folds samples/dropped deltas into
            # fleet_profile_* and rolls the tables up in health()
            h["profile"] = self.profiler.digest()
        if self.ledger is not None:
            # typed segment totals + headroom forecast riding the
            # heartbeat: the fleet router delta-folds the stats into
            # fleet_mem_* and rolls MEM%/HEADROOM up for fleet_top.
            # digest() sweeps (rate-limited) — health() cadence is
            # exactly where the ground-truth cross-check belongs.
            h["mem"] = self.ledger.digest()
        if self._watchdog is not None:
            h["watchdog"] = dict(self._watchdog.health(),
                                 wedge_count=int(self._m_wedges.value))
        return h

    # -- sampling (one strategy per engine == per compiled program) ---------

    def _sample(self, logits, key):
        logits = logits.astype(jnp.float32)
        if self.temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits / self.temperature
        if self.top_k:
            vals, cand = jax.lax.top_k(logits, self.top_k)
            pick = jax.random.categorical(key, vals)
            return jnp.take_along_axis(
                cand, pick[..., None], axis=-1)[..., 0].astype(jnp.int32)
        return jax.random.categorical(key, logits).astype(jnp.int32)

    def _sample_rows(self, logits, keys):
        """Batched sampling with ONE key per row: logits [N, V],
        keys [N, 2]. Every row's draw depends only on its own (key,
        logits) — `vmap` of the single-row sampler — so a row sampled
        inside a width-N batch is bit-identical to the same row
        sampled inside a width-M batch. That row independence is what
        makes speculative verify (which folds K+1 positions into the
        batch dim) reproduce the plain decode scan's tokens exactly;
        a single-key `categorical` over the whole batch would draw
        batch-shape-dependent noise and break it."""
        logits = logits.astype(jnp.float32)
        if self.temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits / self.temperature
        if self.top_k:
            vals, cand = jax.lax.top_k(logits, self.top_k)
            pick = jax.vmap(jax.random.categorical)(keys, vals)
            return jnp.take_along_axis(
                cand, pick[:, None], axis=-1)[:, 0].astype(jnp.int32)
        return jax.vmap(jax.random.categorical)(keys,
                                                logits).astype(jnp.int32)

    # -- compiled programs --------------------------------------------------

    def _counting(self, name, fn, donate_argnums=()):
        """jit through the RecompileTracer: its per-site counter bumps
        exactly when jax (re)traces, i.e. on every compile — the
        zero-recompile assertion's ground truth — and each trace lands
        in the recompile report with its signature + compile wall time.
        Steady-state host overhead is two dict reads per call."""
        def wrapped(*args):
            from ..autograd import no_grad
            with no_grad():
                return fn(*args)

        kw = {"donate_argnums": donate_argnums} \
            if (self.donate and donate_argnums) else {}
        self._aot_programs[name] = (wrapped, kw)
        return self.tracer.jit(name, wrapped, **kw)

    def _layer_caches(self, pages, page_table, positions):
        return [PagedLayerCache(k, v, page_table, positions,
                                k_scale=ks, v_scale=vs,
                                use_flash=self.use_flash)
                for (k, v, ks, vs) in pages]

    @staticmethod
    def _unwrap_pages(new_caches):
        def arr(x):
            return x._value if isinstance(x, Tensor) else x
        return [(arr(c.k_pages), arr(c.v_pages),
                 None if c.k_scale is None else arr(c.k_scale),
                 None if c.v_scale is None else arr(c.v_scale))
                for c in new_caches]

    def _model_token_step(self, params, buffers, tokens, pages,
                          page_table, positions):
        """One batched single-token forward through the paged cache.
        tokens [B] int32; returns (last_logits [B, V] f32, new pages)."""
        caches = self._layer_caches(pages, page_table, positions)
        out = functional_call(
            self.model, params, buffers, Tensor(tokens[:, None]),
            use_cache=False, cache=caches,
            cache_index=Tensor(positions))
        logits_t, new_caches = out
        logits = logits_t._value if isinstance(logits_t, Tensor) \
            else logits_t
        return (logits[:, -1].astype(jnp.float32),
                self._unwrap_pages(new_caches))

    def _build_decode_fn(self):
        steps = self.steps_per_dispatch
        pad = self.pad_token_id

        def decode(params, buffers, pages, page_table, seq_lens,
                   last_tokens, active, done, emitted, max_new, eos,
                   key_base):
            def step(carry, _):
                (pages, seq_lens, last, done, emitted) = carry
                live = active & ~done
                logits, pages = self._model_token_step(
                    params, buffers, last, pages, page_table, seq_lens)
                # token index e = emitted-so-far keys the draw:
                # fold_in(base, e) — the stream is a function of the
                # request and index, never of dispatch scheduling
                keys = jax.vmap(jax.random.fold_in)(key_base, emitted)
                nxt = self._sample_rows(logits, keys)
                nxt = jnp.where(live, nxt, jnp.int32(pad))
                emitted = emitted + live.astype(jnp.int32)
                stop = (emitted >= max_new) | ((eos >= 0) & (nxt == eos))
                done = done | (live & stop)
                seq_lens = seq_lens + live.astype(jnp.int32)
                last = jnp.where(live, nxt, last)
                return (pages, seq_lens, last, done, emitted), nxt

            carry = (pages, seq_lens, last_tokens, done, emitted)
            carry, toks = jax.lax.scan(step, carry, None, length=steps)
            pages, seq_lens, last, done, emitted = carry
            return (toks, pages, seq_lens, last, done, emitted)

        # donate the page pool (arg 2): decode updates it in place
        return self._counting("decode", decode, donate_argnums=(2,))

    def _build_spec_verify_fn(self):
        """The speculative-verify program: ONE batched dispatch scores
        all spec_k+1 candidate positions of every slot by FOLDING them
        into the batch dimension — lane (b, j) = row b*(K+1)+j carries
        slot b's candidate token at position seq_lens[b]+j, with the
        slot's page-table row repeated across its lanes. Within each
        layer the paged cache writes every lane's K/V row first (one
        scatter, distinct (page, row) targets because positions are
        consecutive) and then attends with lens = position+1, so lane
        (b, j) sees exactly the context plain decode would have at that
        position. _model_token_step is the SAME function the decode
        scan calls, per-row computations are batch-width invariant, and
        each position samples with fold_in(key_base, emitted+j) — the
        identical key plain decode would use — so the returned tokens
        are bit-identical to non-speculative decode wherever the draft
        context matches (the host commits exactly that prefix + one
        correction, r19-tail-style: rows written past the commit point
        are masked by lens and overwritten by the next dispatch).

        Lanes whose position would exceed max_seq_len have their WHOLE
        table row redirected to the trash page (never a clamp into a
        real page): the table keeps the plain-decode width so attention
        reduction shapes — and therefore bitwise numerics — are
        untouched, and the host never commits such positions (submit()
        bounds prompt+max_new by max_seq_len)."""
        k1 = self.spec_k + 1
        b = self.max_slots

        def verify(params, buffers, pages, page_table, seq_lens,
                   last_tokens, drafts, key_base, emitted):
            toks_f = jnp.concatenate(
                [last_tokens[:, None], drafts], axis=1).reshape(-1)
            offs = jnp.arange(k1, dtype=jnp.int32)
            pos_f = (seq_lens[:, None] + offs[None, :]).reshape(-1)
            pt_f = jnp.repeat(page_table, k1, axis=0)
            pt_f = jnp.where((pos_f >= self.max_seq_len)[:, None],
                             jnp.int32(TRASH_PAGE), pt_f)
            logits, pages = self._model_token_step(
                params, buffers, toks_f, pages, pt_f, pos_f)
            idx_f = (emitted[:, None] + offs[None, :]).reshape(-1)
            keys = jax.vmap(jax.random.fold_in)(
                jnp.repeat(key_base, k1, axis=0), idx_f)
            true = self._sample_rows(logits, keys)
            return true.reshape(b, k1), pages

        return self._counting("spec_verify", verify, donate_argnums=(2,))

    def _prefill_fn(self, bucket):
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn

        def prefill(params, buffers, pages, ids, true_len, pages_vec,
                    key):
            s_b = ids.shape[1]
            mask = (jnp.arange(s_b)[None, :]
                    < true_len).astype(jnp.int32)
            out = functional_call(self.model, params, buffers,
                                  Tensor(ids), attention_mask=Tensor(mask),
                                  use_cache=True)
            logits_t, caches = out
            logits = logits_t._value if isinstance(logits_t, Tensor) \
                else logits_t

            def arr(x):
                return x._value if isinstance(x, Tensor) else x

            new_pages, dense_kv = [], []
            for (k, v, ks, vs), layer in zip(pages, caches):
                kd, vd = arr(layer[0]), arr(layer[1])
                new_pages.append(write_prompt_kv(
                    k, v, ks, vs, kd, vd, pages_vec))
                # the dense prompt K/V ride back out so the prefix
                # index can pin host-side f32 copies of shareable
                # pages — device buffers, no extra compute
                dense_kv.append((kd, vd))
            last = jax.lax.dynamic_index_in_dim(
                logits[0], true_len - 1, keepdims=False)
            tok = self._sample(last[None, :], key)[0]
            return tok, new_pages, dense_kv

        fn = self._counting(f"prefill_{bucket}", prefill,
                            donate_argnums=(2,))
        self._prefill_fns[bucket] = fn
        return fn

    def _tail_prefill_fn(self, tb):
        """The prefix-cache HIT program for tail bucket ``tb``: the
        matched prefix arrives as dense host-pinned f32 K/V buffers
        (padded to max_seq_len so the program is shape-stable across
        hits), the tail tokens run the models' static-cache multi-token
        forward at cache_index=cached_len — positions, RoPE and the
        causal mask all line up with what a full prefill computes for
        those rows — and only the tail K/V is written into (private)
        pages. One program per tail bucket, zero recompiles after
        warmup; donation matches the full-prefill contract."""
        fn = self._tail_prefill_fns.get(tb)
        if fn is not None:
            return fn

        def tail_prefill(params, buffers, pages, kpre, vpre, ids,
                         cached_len, true_tail, pages_vec, key):
            def arr(x):
                return x._value if isinstance(x, Tensor) else x

            caches = []
            for kp, vp in zip(kpre, vpre):
                pad = jnp.zeros(kp.shape[:1] + (tb,) + kp.shape[2:],
                                kp.dtype)
                caches.append((Tensor(jnp.concatenate([kp, pad], 1)),
                               Tensor(jnp.concatenate([vp, pad], 1))))
            out = functional_call(self.model, params, buffers,
                                  Tensor(ids), use_cache=False,
                                  cache=caches,
                                  cache_index=Tensor(cached_len))
            logits_t, new_caches = out
            logits = arr(logits_t)
            new_pages, tail_kv = [], []
            z0 = jnp.int32(0)
            for (k, v, ks, vs), layer in zip(pages, new_caches):
                kb, vb = arr(layer[0]), arr(layer[1])
                kt = jax.lax.dynamic_slice(
                    kb, (z0, cached_len, z0, z0),
                    (1, tb) + kb.shape[2:])
                vt = jax.lax.dynamic_slice(
                    vb, (z0, cached_len, z0, z0),
                    (1, tb) + vb.shape[2:])
                new_pages.append(write_prompt_kv(k, v, ks, vs, kt, vt,
                                                 pages_vec))
                tail_kv.append((kt, vt))
            last = jax.lax.dynamic_index_in_dim(
                logits[0], true_tail - 1, keepdims=False)
            tok = self._sample(last[None, :], key)[0]
            return tok, new_pages, tail_kv

        fn = self._counting(f"tail_prefill_{tb}", tail_prefill,
                            donate_argnums=(2,))
        self._tail_prefill_fns[tb] = fn
        return fn

    def _prefix_dense(self, entry, j):
        """A matched entry's padded [1, max_seq_len, Hkv, D] dense
        prefix K/V, ready for the tail program. Zero per-hit work:
        the index keeps the padded DEVICE buffers (built once at
        registration), and rows beyond the matched boundary are
        irrelevant by construction — the tail program overwrites
        [cached, cached+tb) with the tail's own K/V and causally
        masks everything past that, so the same buffers serve every
        nested boundary of the entry."""
        del j  # every boundary reads the same padded buffers
        return ([k for k, _ in entry.kv], [v for _, v in entry.kv])

    # -- host-side scheduling ----------------------------------------------

    def _finish_request(self, req, status, tokens=None, kv_page_s=0.0,
                        prefix_hit_pages=0, prefix_pages=0,
                        spec_proposed=0, spec_accepted=0):
        """Finish a request that never reached (or is leaving) a slot.
        age_s — submit-to-finish latency — rides the result so tail
        latency is measurable per request, not just per dispatch;
        tenant-tagged requests additionally carry their queue-wait and
        KV-page-seconds (what only the engine can see) and fold into
        the per-tenant usage sketch."""
        self._status_counter(status).inc()
        if status == "expired":
            self._m_deadline.inc()
        elif status == "evicted":
            self._m_evictions.inc()
        age = round(time.monotonic() - req.submitted_at, 6)
        qw = req.queue_wait_s
        if qw is None:   # never admitted: the whole age was queue wait
            qw = time.monotonic() - req.submitted_at
        # usage facts ride EVERY result (the router folds untagged
        # traffic under "anon", and its kv/queue numbers must be as
        # real as a tagged tenant's); the tenant key and the
        # engine-side sketch stay tagged-only
        result = {"id": req.rid,
                  "prompt": req.prompt.tolist(),
                  "tokens": list(tokens or []),
                  "status": status,
                  "queue_wait_s": round(qw, 6),
                  "kv_page_s": round(kv_page_s, 6),
                  "prefix_hit_pages": int(prefix_hit_pages),
                  "prefix_pages": int(prefix_pages),
                  "spec_proposed": int(spec_proposed),
                  "spec_accepted": int(spec_accepted),
                  "age_s": age}
        if req.tenant is not None:
            result["tenant"] = req.tenant
            self.tenants.account(req.tenant,
                                 tokens_in=len(req.prompt),
                                 tokens_out=len(tokens or []),
                                 queue_wait_s=qw,
                                 kv_page_s=kv_page_s, requests=1,
                                 prefix_hit_pages=int(prefix_hit_pages),
                                 prefix_pages=int(prefix_pages),
                                 spec_proposed=int(spec_proposed),
                                 spec_accepted=int(spec_accepted))
        self._finished.append(result)
        self._cancel_pending.discard(req.rid)
        if req.trace is not None and req.admitted_pc is None:
            # never admitted (cancelled/expired/shed in the queue):
            # the queue leg is the whole replica-side story
            self._dtrace_add(req.trace, "queue", req.submitted_pc,
                             outcome=status)
        self.spans.instant("finish", tid=f"req{req.rid}", cat="serve",
                           args={"status": status,
                                 "tokens": len(tokens or []),
                                 "age_s": age})
        from ..observability import flightrec
        flightrec.note("serve_finish", rid=req.rid, status=status,
                       tokens=len(tokens or []), age_s=age)

    def _finish_slot(self, b, status=None):
        """Release slot b and emit its result (status defaults to the
        slot's recorded degradation status, 'ok' for a natural
        finish). Pages return to the free list immediately."""
        slot = self._slots[b]
        req = slot.req
        if req.trace is not None and slot.decode_t0 is not None:
            self._dtrace_add(req.trace, "decode", slot.decode_t0,
                             args={"tokens": len(slot.out_tokens)},
                             outcome=status or slot.status)
        # KV-page-seconds: pages held x admission->release wall — the
        # HBM-residency cost this request charged the pool (tenancy)
        kv_page_s = 0.0
        if req.admitted_pc is not None:
            kv_page_s = len(slot.pages) * max(
                time.perf_counter() - req.admitted_pc, 0.0)
        self._finish_request(req, status or slot.status,
                             slot.out_tokens[:req.max_new_tokens],
                             kv_page_s=kv_page_s,
                             prefix_hit_pages=slot.prefix_hit_pages,
                             prefix_pages=slot.prefix_pages,
                             spec_proposed=slot.spec_proposed,
                             spec_accepted=slot.spec_accepted)
        self.spans.instant("release_pages", tid="sched", cat="serve",
                           args={"rid": req.rid, "slot": b,
                                 "pages": len(slot.pages),
                                 "shared": len(slot.shared),
                                 "status": status or slot.status})
        if slot.shared:
            # refcount-aware release: index-owned pages stay resident
            # for the next hit (they free only through LRU eviction at
            # refcount 0); only the private pages return to the pool
            self.prefix.release(slot.shared)
            self._free_pages.extend(p for p in slot.pages
                                    if p not in slot.shared)
        else:
            self._free_pages.extend(slot.pages)
        self._slots[b] = None
        self._active[b] = False
        self._done[b] = True
        self._page_table[b, :] = TRASH_PAGE
        self._seq_lens[b] = 0
        self._emitted[b] = 0
        self._eos[b] = -1
        self._dev_sched = None  # host state diverged from device

    def _evict(self):
        for b in range(self.max_slots):
            if self._slots[b] is not None and self._done[b]:
                self._finish_slot(b)

    def _apply_cancels(self):
        """Host boundary resolution of cancel(): queued requests leave
        the queue; running ones are marked done for the sweep."""
        if not self._cancel_pending:
            return
        kept = collections.deque()
        for req in self._queue:
            if req.rid in self._cancel_pending:
                self._finish_request(req, "cancelled")
            else:
                kept.append(req)
        self._queue = kept
        for b in range(self.max_slots):
            slot = self._slots[b]
            if slot is not None and slot.req.rid in self._cancel_pending:
                self._cancel_pending.discard(slot.req.rid)
                slot.status = "cancelled"
                self._done[b] = True
                self._dev_sched = None

    def _expire_deadlines(self):
        """Deadline expiry, host boundaries only (zero-recompile): a
        queued request past its deadline never admits; a running one
        stops decoding this round and returns its partial tokens."""
        now = time.monotonic()
        kept = collections.deque()
        for req in self._queue:
            if req.deadline is not None and now > req.deadline:
                self._finish_request(req, "expired")
            else:
                kept.append(req)
        self._queue = kept
        for b in range(self.max_slots):
            slot = self._slots[b]
            if slot is None or self._done[b]:
                continue
            dl = slot.req.deadline
            if dl is not None and now > dl:
                slot.status = "expired"
                self._done[b] = True
                self._dev_sched = None

    def _victim_slot(self, priority):
        """Lowest-priority running slot strictly below `priority`
        (ties: latest admission goes first — it has sunk the least
        decode work)."""
        best = None
        key = None
        for b in range(self.max_slots):
            slot = self._slots[b]
            if slot is None or self._done[b]:
                continue
            if slot.req.priority >= priority:
                continue
            k = (slot.req.priority, -slot.admit_seq)
            if key is None or k < key:
                best, key = b, k
        return best

    def _admit(self):
        # injected page exhaustion: the free list READS as empty for
        # this round (pages are not actually lost), driving the
        # admission policy exactly like a real shortage
        exhausted = faults.pull("page_exhaustion", self._rounds) \
            is not None
        while self._queue:
            req = self._queue[0]
            free_slot = next((b for b in range(self.max_slots)
                              if self._slots[b] is None), None)
            need_pages = -(-(len(req.prompt) + req.max_new_tokens)
                           // self.page_size)
            have = 0 if exhausted else len(self._free_pages)
            short_pages = have < need_pages
            if short_pages and not exhausted \
                    and self.prefix is not None:
                # reclaim BEFORE the admission policy bites: idle
                # shared prefixes (refcount 0) are cache, not load —
                # LRU-evict them instead of rejecting/preempting work.
                # Under INJECTED exhaustion the free list must keep
                # reading as empty, so no reclaim then.
                freed = self.prefix.evict(need_pages - have)
                if freed:
                    self._free_pages.extend(freed)
                    self._mem_sync_prefix()
                    self.spans.instant(
                        "prefix_evict", tid="sched", cat="serve",
                        args={"pages": len(freed)})
                    have = len(self._free_pages)
                    short_pages = have < need_pages
            if free_slot is not None and not short_pages:
                if self.ledger is not None:
                    # advisory admission consult before page
                    # allocation: counts checks and would-not-fit
                    # verdicts (engine_mem_admission_*); hard mode
                    # already screened at submit(), so admission
                    # itself never blocks here
                    self.ledger.admission_check(
                        need_pages * self._page_bytes)
                self._queue.popleft()
                self._admit_one(free_slot, req, need_pages)
                continue
            if self.admission_policy == "reject" and short_pages \
                    and free_slot is not None:
                # pages are the scarce resource here; a merely-full
                # slot pool turns over every round and is not worth a
                # rejection
                self._queue.popleft()
                self._finish_request(req, "rejected")
                continue
            if self.admission_policy == "evict" and not exhausted:
                # preemption frees a slot AND its pages, so it covers
                # both shortages; under INJECTED exhaustion freed
                # pages would still read as absent — evicting then
                # would be a death spiral, so fall through to wait
                victim = self._victim_slot(req.priority)
                if victim is None:
                    return  # nobody lower-priority: back-pressure
                self._finish_slot(victim, "evicted")
                continue  # re-check the head against freed capacity
            return  # back-pressure: retry next boundary

    def _prefix_lookup(self, req):
        """(entry, matched_pages) when the HIT path should run, else
        None — and fold the hit/miss accounting. A hit additionally
        requires its tail bucket pre-traced (warmup): caching must
        never introduce a mid-traffic compile, so a cold engine takes
        the full-prefill path unconditionally. An engine that never
        armed ANY tail bucket keeps the cache fully dormant (no
        accounting, no page retention): it could never serve a hit,
        so retained pages would only shrink the pool."""
        if self.prefix is None or not self._warmed_tail_buckets:
            return None
        fps = req.prefix_fps
        if fps is None:  # e.g. cache enabled after submit — recompute
            fps = prefix_fingerprints(req.prompt, self.page_size)
            req.prefix_fps = fps
        self.prefix.total_pages += len(fps)
        m = self.prefix.match(fps)
        if m is not None:
            tail = len(req.prompt) - m[1] * self.page_size
            if self._bucket_for(tail) in self._warmed_tail_buckets:
                self.prefix.hits += 1
                self.prefix.hit_pages += m[1]
                return m
        if len(fps) >= self.prefix.min_pages:
            self.prefix.misses += 1
        return None

    def _prefix_register(self, req, pages, kv_host_fn):
        """Register a prompt's boundary fingerprints after its pages
        were written (miss path: all of them; hit path: the extension
        beyond the matched boundary). kv_host_fn materializes the host
        f32 dense K/V lazily — only paid when something new registers.
        Returns the set of slot pages the index now owns. Dormant
        (never-armed) engines register nothing — see _prefix_lookup."""
        if self.prefix is None or not self._warmed_tail_buckets:
            return frozenset()
        fps = req.prefix_fps or []
        if len(fps) < self.prefix.min_pages or self.prefix.covers(fps):
            return frozenset()
        adopted, freed = self.prefix.insert(fps, pages, kv_host_fn(),
                                            pin=True)
        if freed:
            self._free_pages.extend(freed)
        self._mem_sync_prefix()
        return adopted

    def _mem_sync_prefix(self):
        """Refresh the ledger's prefix_sidecar level from the index's
        own sidecar inventory (the level channel: idempotent absolute
        sets at the seams that mutate it, re-asserted by every sweep's
        audit). No-op when either plane is dormant."""
        if self.ledger is not None and self.prefix is not None:
            self.ledger.set_level("prefix_sidecar",
                                  self.prefix.sidecar_bytes())

    def _mem_audit(self):
        """The ledger's periodic sweep hook: cross-check prefix-index
        refcounts against live page-table references (the release-on-
        failover leak class) and re-sync the sidecar level. Returns
        problem strings; sweep counts them into
        engine_mem_audit_failures_total."""
        if self.prefix is None:
            return []
        live = {}
        for slot in self._slots:
            if slot is None:
                continue
            for p in slot.shared:
                live[p] = live.get(p, 0) + 1
        problems = self.prefix.audit(live_refs=live)
        self._mem_sync_prefix()
        return problems

    def _admit_one(self, b, req, need_pages):
        req.queue_wait_s = time.monotonic() - req.submitted_at
        self._m_queue_wait.observe(req.queue_wait_s)
        # span: the queue-wait leg closes at admission (one lane per
        # request — Perfetto shows queue -> prefill -> finish stacked)
        self.spans.add("queue_wait", req.submitted_pc,
                       tid=f"req{req.rid}", cat="serve",
                       args={"rid": req.rid, "slot": b})
        # ONE host-side split per admission: `sub` seeds this request's
        # whole token stream (prefill samples with it directly; decode/
        # verify fold it with each token's emitted index). The split
        # order — admission order — is the only thing the stream
        # depends on, so replay and failover reproduce it exactly.
        self._rng, sub = jax.random.split(self._rng)
        with self._phase("prefix_admit"):
            hit = self._prefix_lookup(req)
        if hit is not None:
            tok, pages, shared, t_post = self._prefill_hit(
                b, req, need_pages, hit, sub)
        else:
            tok, pages, shared, t_post = self._prefill_full(
                b, req, need_pages, sub)
        self._key_base[b] = np.asarray(sub)
        if self._spec is not None and self._warmed_spec:
            self._spec.on_admit(self, b, req)

        self._admit_seq += 1
        slot = _Slot(req, pages, admit_seq=self._admit_seq)
        slot.shared = frozenset(shared)
        slot.prefix_hit_pages = 0 if hit is None else hit[1]
        slot.prefix_pages = len(req.prefix_fps or [])
        self._slots[b] = slot
        self._slots[b].decode_t0 = t_post
        self._slots[b].out_tokens.append(tok)
        row = np.full((self.max_pages_per_seq,), TRASH_PAGE, np.int32)
        row[:need_pages] = pages
        self._page_table[b] = row
        self._seq_lens[b] = len(req.prompt)
        self._last_tokens[b] = tok
        self._emitted[b] = 1
        self._max_new[b] = req.max_new_tokens
        self._eos[b] = -1 if req.eos_token_id is None \
            else int(req.eos_token_id)
        self._active[b] = True
        self._done[b] = bool(req.max_new_tokens <= 1
                             or (req.eos_token_id is not None
                                 and tok == req.eos_token_id))
        self._dev_sched = None  # host state diverged from device

    def _prefill_full(self, b, req, need_pages, key):
        """The miss path: full bucketed prefill (the pre-prefix-cache
        admission body, unchanged), plus prefix registration of the
        freshly written prompt pages. Returns (first token, pages,
        index-owned pages, prefill-end perf_counter)."""
        ps = self.page_size
        lp = len(req.prompt)
        # pow2 bucket, rounded UP to whole pages (_bucket_for — ONE
        # formula, shared with warmup so a pre-traced bucket is
        # exactly the one admission will ask for): write_prompt_kv
        # reshapes the bucket into page blocks, and a page_size that is
        # a multiple of 8 but not a power of two (e.g. 24) would
        # otherwise leave bucket % ps != 0. Bucket count stays bounded
        # (one per pow2 size), so the no-fresh-trace property holds.
        bucket = self._bucket_for(lp)
        nb = bucket // ps
        pages = [self._free_pages.pop() for _ in range(need_pages)]
        # bucket tail blocks beyond the allocation write to the trash
        # page (write_prompt_kv's contract)
        pages_vec = np.full((nb,), TRASH_PAGE, np.int32)
        pages_vec[:min(need_pages, nb)] = pages[:nb]
        ids = np.full((1, bucket), self.pad_token_id, np.int32)
        ids[0, :lp] = req.prompt

        fn = self._prefill_fn(bucket)
        t_pre = time.perf_counter()
        with self._phase(f"prefill_{bucket}"):
            with self._watch(f"prefill_{bucket}"):
                tok, new_pages, dense_kv = fn(
                    self._params, self._buffers, self._pages,
                    jnp.asarray(ids), jnp.int32(lp),
                    jnp.asarray(pages_vec), key)
            self._pages = new_pages
            tok = int(tok)  # host sync: the first token exists NOW
        self._m_ttft.observe(time.monotonic() - req.submitted_at)
        # the int(tok) sync above bounds the span at real prefill work
        self.spans.add(f"prefill_{bucket}", t_pre, tid=f"req{req.rid}",
                       cat="serve", args={"rid": req.rid, "slot": b,
                                          "pages": need_pages})
        # distributed-trace legs: the queue-wait leg closed at t_pre,
        # the prefill leg at the sync above (dtrace no-ops untraced)
        req.admitted_pc = t_pre
        t_post = time.perf_counter()
        self._dtrace_add(req.trace, "queue", req.submitted_pc, t_pre,
                         args={"slot": b})
        self._dtrace_add(req.trace, f"prefill_{bucket}", t_pre, t_post,
                         args={"pages": need_pages,
                               "prompt_len": lp})
        def kv_dense():
            # padded [1, max_seq_len, Hkv, D] DEVICE buffers for the
            # index: no host round-trip, and jnp.pad on a fixed shape
            # set compiles once per bucket then replays — admission
            # never stalls on eager transfers
            pre = self.max_seq_len
            out = []
            for k, v in dense_kv:
                pad = pre - k.shape[1]
                if pad > 0:
                    k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
                    v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
                out.append((k, v))
            return out

        with self._phase("prefix_admit"):
            shared = self._prefix_register(req, pages, kv_dense)
        return tok, pages, shared, t_post

    def _prefill_hit(self, b, req, need_pages, hit, key):
        """The prefix-cache HIT path: map the matched entry's shared
        pages into this slot (COW — they are never written again),
        allocate private pages for the tail + decode, and run the
        short tail-prefill program. The admission key seeds the first
        token exactly like a full prefill, so the token stream is the
        OFF path's stream whenever logits agree. Returns like
        _prefill_full."""
        entry, j = hit
        ps = self.page_size
        lp = len(req.prompt)
        cached = j * ps
        tail = lp - cached      # >= 1: boundaries stop before the end
        tb = self._bucket_for(tail)
        nbt = tb // ps
        priv = [self._free_pages.pop()
                for _ in range(need_pages - j)]
        shared_pages = self.prefix.acquire(entry)
        pages = shared_pages + priv
        pages_vec = np.full((nbt,), TRASH_PAGE, np.int32)
        pages_vec[:min(len(priv), nbt)] = priv[:nbt]
        ids = np.full((1, tb), self.pad_token_id, np.int32)
        ids[0, :tail] = req.prompt[cached:]
        kpre, vpre = self._prefix_dense(entry, j)

        fn = self._tail_prefill_fn(tb)
        t_pre = time.perf_counter()
        with self._phase(f"prefill_{tb}"):
            with self._watch(f"tail_prefill_{tb}"):
                tok, new_pages, tail_kv = fn(
                    self._params, self._buffers, self._pages, kpre,
                    vpre, jnp.asarray(ids), jnp.int32(cached),
                    jnp.int32(tail), jnp.asarray(pages_vec), key)
            self._pages = new_pages
            tok = int(tok)  # host sync: the first token exists NOW
        self._m_ttft.observe(time.monotonic() - req.submitted_at)
        self.spans.add(f"tail_prefill_{tb}", t_pre,
                       tid=f"req{req.rid}", cat="serve",
                       args={"rid": req.rid, "slot": b,
                             "pages": need_pages, "cached_pages": j})
        req.admitted_pc = t_pre
        t_post = time.perf_counter()
        self._dtrace_add(req.trace, "queue", req.submitted_pc, t_pre,
                         args={"slot": b})
        self._dtrace_add(req.trace, f"tail_prefill_{tb}", t_pre,
                         t_post, args={"pages": need_pages,
                                       "prompt_len": lp,
                                       "cached_pages": j})
        # COW accounting: the partial-page tail re-materialized
        # privately instead of writing the shared pages
        self.prefix.cow_copies += min(-(-tail // ps), len(priv))
        shared = set(shared_pages)
        jm = len(req.prefix_fps or [])
        if jm > j:
            # extension-on-hit: this prompt proves longer boundaries —
            # splice the entry's prefix K/V with the tail rows just
            # computed and register them (prefix view + tail copy).
            # The splice width is the whole (clipped) tail bucket, not
            # the exact extension: every newly proven boundary sits at
            # <= cached + tail <= cached + width, and rows past the
            # deepest boundary are past-boundary garbage the tail
            # program overwrites/masks on any future hit. Bucketed
            # widths keep the eager-op shape set identical to the
            # ladder warmup() pre-compiled — no mid-traffic compile.
            width = min(tb, self.max_seq_len - cached)

            def kv_dense():
                return [(jax.lax.dynamic_update_slice(
                            ek, kt if width == tb else kt[:, :width],
                            (0, cached, 0, 0)),
                         jax.lax.dynamic_update_slice(
                            ev, vt if width == tb else vt[:, :width],
                            (0, cached, 0, 0)))
                        for (ek, ev), (kt, vt)
                        in zip(entry.kv, tail_kv)]

            with self._phase("prefix_admit"):
                shared |= self._prefix_register(req, pages, kv_dense)
        return tok, pages, shared, t_post

    def _watch(self, op):
        """Watchdog heartbeat around one dispatch (nullcontext when no
        watchdog is armed)."""
        import contextlib
        if self._watchdog is None:
            return contextlib.nullcontext()
        return self._watchdog.watch(op)

    def _phase(self, name):
        """Serving-phase marker for the continuous profiler
        (observability.contprof) — nullcontext when no profiler is
        armed, the _watch idiom. One GIL-atomic dict write per
        boundary; the sampler tags every stack it takes from this
        thread with the innermost open phase."""
        import contextlib
        if self.profiler is None:
            return contextlib.nullcontext()
        from ..observability import contprof
        return contprof.phase(name)

    def _dispatch_decode(self):
        # the phase covers the WHOLE dispatch — device call AND the
        # host-side sync + slot bookkeeping after it (which the
        # watchdog window deliberately excludes)
        with self._phase("decode"):
            self._dispatch_decode_impl()

    def _dispatch_decode_impl(self):
        emitted_before = self._emitted.copy()
        t0 = time.perf_counter()
        if self._dev_sched is None:
            self._dev_sched = tuple(
                jnp.asarray(a) for a in
                (self._page_table, self._seq_lens, self._last_tokens,
                 self._active, self._done, self._emitted,
                 self._max_new, self._eos, self._key_base))
        (pt_d, sl_d, lt_d, ac_d, dn_d, em_d, mn_d, eos_d, kb_d) = \
            self._dev_sched

        def dispatch():
            # injected transients fire BEFORE the execute, so a retry
            # re-submits a page pool that was never donated away
            faults.maybe_raise("dispatch_error", self._rounds)
            return self._decode_fn(
                self._params, self._buffers, self._pages,
                pt_d, sl_d, lt_d, ac_d, dn_d, em_d, mn_d, eos_d, kb_d)

        from ..resilience.retry import retryable_for
        with self._watch("decode"):
            # slow-step seam sits inside the watchdog window: a wedged
            # dispatch and an injected stall look identical to health()
            faults.maybe_sleep("slow_step", self._rounds)
            (toks, pages, seq_lens, last, done,
             emitted) = call_with_retries(
                dispatch, retries=self.dispatch_retries,
                retryable=retryable_for(self.donate),
                stats=self.retry_stats)
        self._pages = pages
        # decode only advances these four; the rest stay device-valid
        self._dev_sched = (pt_d, seq_lens, last, ac_d, done, emitted,
                           mn_d, eos_d, kb_d)
        toks = np.asarray(toks)                     # [steps, B]
        # np.array (copy): np.asarray of a jax array is a read-only
        # view, and eviction writes these in place
        self._seq_lens = np.array(seq_lens)
        self._last_tokens = np.array(last)
        self._done = np.array(done)
        self._emitted = np.array(emitted)
        # the np.array() conversions above force the device sync, so
        # this timestamp bounds real work, not async dispatch
        self.last_dispatch_s = time.perf_counter() - t0
        n_new = int((self._emitted - emitted_before).sum())
        live = int(sum(1 for s in self._slots if s is not None))
        # all live requests share one batched dispatch — ONE span on
        # the shared decode lane, carrying who rode it
        self.spans.add("decode", t0, t0 + self.last_dispatch_s,
                       tid="decode", cat="serve",
                       args={"round": self._rounds, "tokens": n_new,
                             "live_slots": live})
        from ..observability import flightrec
        flightrec.note("serve_dispatch", round=self._rounds,
                       tokens=n_new, live_slots=live,
                       wall_s=round(self.last_dispatch_s, 6))
        self.decode_seconds += self.last_dispatch_s
        self.decode_tokens += n_new
        self.decode_dispatches += 1
        # histograms ride the sync that already happened above — one
        # count-weighted observe per dispatch, nothing per token
        self._m_dispatch.observe(self.last_dispatch_s)
        self._m_decode_dispatches.inc()
        if n_new:
            self._m_tok.observe(self.last_dispatch_s / n_new,
                                count=n_new)
            self._m_decode_tokens.inc(n_new)
        for b in range(self.max_slots):
            slot = self._slots[b]
            if slot is None:
                continue
            n = int(self._emitted[b] - emitted_before[b])
            if n:
                # live steps are the first n of the scan (done is
                # monotonic within a dispatch)
                slot.out_tokens.extend(int(t) for t in toks[:n, b])

    def _dispatch_spec(self):
        with self._phase("spec_verify"):
            self._dispatch_spec_impl()

    def _dispatch_spec_impl(self):
        """One speculative decode round: the proposer drafts spec_k
        tokens per slot, the folded verify program scores all spec_k+1
        positions in ONE dispatch, and the host commits the longest
        draft prefix the target's own sampler reproduced plus exactly
        one correction (or the bonus token after a full accept) —
        every live slot advances >= 1 token per dispatch, and every
        committed token is bit-identical to plain decode's.

        The rewind is host-side bookkeeping, the r19 tail contract:
        seq_lens advances only over the committed tokens, so KV rows
        written past the commit point are masked by the attention
        length and overwritten by the next dispatch (whose verify span
        seq_lens..seq_lens+spec_k covers them) — page contents never
        roll back on device."""
        K = self.spec_k
        emitted_before = self._emitted.copy()
        t0 = time.perf_counter()
        # proposer cost — ngram host lookup or the draft model's own
        # dispatch — counts inside the decode window: acceptance gains
        # must beat it for tok/s to move
        drafts = self._spec.propose(self)           # [B, K] np.int32
        sched = tuple(jnp.asarray(a) for a in
                      (self._page_table, self._seq_lens,
                       self._last_tokens, drafts, self._key_base,
                       self._emitted))

        def dispatch():
            faults.maybe_raise("dispatch_error", self._rounds)
            return self._spec_verify_fn(
                self._params, self._buffers, self._pages, *sched)

        from ..resilience.retry import retryable_for
        with self._watch("spec_verify"):
            faults.maybe_sleep("slow_step", self._rounds)
            true, pages = call_with_retries(
                dispatch, retries=self.dispatch_retries,
                retryable=retryable_for(self.donate),
                stats=self.retry_stats)
        self._pages = pages
        true = np.asarray(true)                     # [B, K+1]; syncs
        proposed = accepted = committed = 0
        for b in range(self.max_slots):
            slot = self._slots[b]
            if slot is None or not self._active[b] or self._done[b]:
                continue
            e = int(emitted_before[b])
            mx = int(self._max_new[b])
            eos = int(self._eos[b])
            com = acc = 0
            done = False
            for j in range(K + 1):
                # position j attends rows 0..seq_lens+j-1: the prompt
                # plus drafts 0..j-1 — valid exactly while every
                # earlier draft matched, which is when this loop is
                # still running (j == K is the bonus token, reached
                # only after a full accept)
                t = int(true[b, j])
                slot.out_tokens.append(t)
                com += 1
                hit = j < K and t == int(drafts[b, j])
                acc += int(hit)
                if (e + com >= mx) or (eos >= 0 and t == eos):
                    done = True
                    break
                if j < K and not hit:
                    break               # correction committed; rewind
            self._seq_lens[b] += com
            self._emitted[b] = e + com
            self._last_tokens[b] = slot.out_tokens[-1]
            if done:
                self._done[b] = True
            slot.spec_proposed += K
            slot.spec_accepted += acc
            proposed += K
            accepted += acc
            committed += com
        self._dev_sched = None  # host state diverged from device
        self.last_dispatch_s = time.perf_counter() - t0
        live = int(sum(1 for s in self._slots if s is not None))
        self.spans.add("spec_verify", t0, t0 + self.last_dispatch_s,
                       tid="decode", cat="serve",
                       args={"round": self._rounds, "tokens": committed,
                             "proposed": proposed, "accepted": accepted,
                             "live_slots": live})
        from ..observability import flightrec
        flightrec.note("serve_spec_dispatch", round=self._rounds,
                       tokens=committed, proposed=proposed,
                       accepted=accepted, live_slots=live,
                       wall_s=round(self.last_dispatch_s, 6))
        self.decode_seconds += self.last_dispatch_s
        self.decode_tokens += committed
        self.decode_dispatches += 1
        self._m_dispatch.observe(self.last_dispatch_s)
        self._m_decode_dispatches.inc()
        self._m_spec_dispatches.inc()
        if proposed:
            self._m_spec_proposed.inc(proposed)
        if accepted:
            self._m_spec_accepted.inc(accepted)
        if committed:
            self._m_tok.observe(self.last_dispatch_s / committed,
                                count=committed)
            self._m_decode_tokens.inc(committed)
