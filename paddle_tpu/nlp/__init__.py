"""NLP model zoo (PaddleNLP parity subset).

ref: PaddleNLP paddlenlp/transformers/{gpt,bert,ernie}/modeling.py and
tokenizer_utils.py. TPU-native: every model is built from mesh-aware
layers (mpu Column/Row parallel linears, vocab-parallel embedding) so the
same module runs dense on one chip and tensor-parallel under a Mesh.
"""
from .gpt import (  # noqa: F401
    GPTConfig, GPTModel, GPTForCausalLM, GPTLMHeadModel,
    GPTPretrainingCriterion, GPT_CONFIGS,
)
