"""NLP model zoo (PaddleNLP parity subset).

ref: PaddleNLP paddlenlp/transformers/{gpt,bert,ernie}/modeling.py and
tokenizer_utils.py. TPU-native: every model is built from mesh-aware
layers (mpu Column/Row parallel linears, vocab-parallel embedding) so the
same module runs dense on one chip and tensor-parallel under a Mesh.
"""
from .gpt import (  # noqa: F401
    GPTConfig, GPTModel, GPTForCausalLM, GPTLMHeadModel,
    GPTPretrainingCriterion, GPT_CONFIGS,
)
from .bert import (  # noqa: F401
    BertConfig, BertModel, BertForPretraining, BertPretrainingCriterion,
    BertForMaskedLM, BertForSequenceClassification,
    BertForTokenClassification, BertForQuestionAnswering, BERT_CONFIGS,
)
from .ernie import (  # noqa: F401
    ErnieConfig, ErnieModel, ErnieForPretraining, ErniePretrainingCriterion,
    ErnieForMaskedLM, ErnieForSequenceClassification,
    ErnieForTokenClassification, ErnieForQuestionAnswering, ERNIE_CONFIGS,
)
from .llama import (  # noqa: F401
    LlamaConfig, LlamaModel, LlamaForCausalLM,
    LlamaPretrainingCriterion, LLAMA_CONFIGS,
)
from .tokenizer import (  # noqa: F401
    BasicTokenizer, WordpieceTokenizer, BertTokenizer, GPTTokenizer,
)
from . import generation  # noqa: F401
# continuous-batching serving engine (paged KV cache); the Pallas
# paged kernels load lazily inside it, so this import stays light
from .serving import ServingEngine  # noqa: F401
