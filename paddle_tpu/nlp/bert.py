"""BERT encoder family.

ref parity: PaddleNLP paddlenlp/transformers/bert/modeling.py (BertModel,
BertForPretraining, BertPretrainingCriterion, BertForSequenceClassification,
BertForTokenClassification, BertForQuestionAnswering, BertForMaskedLM) and
bert/configuration.py pretrained configs.

TPU-native design: same mesh-aware building blocks as gpt.py — mpu
Column/RowParallelLinear projections, VocabParallelEmbedding, flash-capable
scaled_dot_product_attention (bidirectional, is_causal=False), post-LN
residual blocks (the reference BERT's normalize_before=False). The MLM head
ties the word embedding via parallel_matmul and its loss is vocab-parallel
safe through ParallelCrossEntropy.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..nn import functional as F
from ..nn.initializer import Normal, ParamAttr
from ..nn.layer import Layer
from ..nn.layers_common import Dropout, Embedding, LayerList, Linear
from ..nn.layers_norm import LayerNorm
from ..tensor import Tensor
from ..distributed.fleet.mpu import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy, parallel_matmul)


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 0  # 0 -> 4*hidden
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0
    pool_act: str = "tanh"
    use_flash_attention: bool = True
    num_labels: int = 2
    # stacked [L,...] params + one lax.scan over the encoder blocks
    # (nn/scan_stack.py): O(1-block) compiled program. Training/inference
    # without per-layer outputs only; eager-tape training is gated.
    scan_layers: bool = False
    # one [h, 3h] qkv matmul (Megatron head-interleave; convert
    # checkpoints with gpt.fuse_qkv_state / split_qkv_state)
    fused_qkv: bool = False
    # fuse each residual add into its following LayerNorm with one
    # Pallas pass (both block sites in post-LN; ops/pallas/fused_ln.py)
    fused_ln: bool = False
    # MLM masked-position gather: only ~15% of pretraining positions
    # carry labels, yet the LM head computes [B,S,vocab] logits for all
    # of them (~20% of the step's FLOPs at base scale). With capacity
    # c > 0, training gathers at most ceil(c*B*S) masked positions
    # (STATIC shape — TPU/jit-safe) before the transform+decode, so
    # head FLOPs and logits memory shrink ~1/c-fold. Loss is EXACTLY
    # the baseline's while the masked count fits the capacity; overflow
    # drops the excess positions (pick c with slack over the mask rate
    # — 0.25 for the standard 15%). ref: Megatron/ERNIE pretraining
    # gathers masked tokens the same way before the vocab projection.
    mlm_gather_capacity: float = 0.0

    def __post_init__(self):
        if not self.intermediate_size:
            self.intermediate_size = 4 * self.hidden_size

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


# ref: bert/configuration.py BERT_PRETRAINED_INIT_CONFIGURATION
BERT_CONFIGS = {
    "bert-base-uncased": dict(vocab_size=30522, hidden_size=768,
                              num_hidden_layers=12, num_attention_heads=12),
    "bert-large-uncased": dict(vocab_size=30522, hidden_size=1024,
                               num_hidden_layers=24, num_attention_heads=16),
    "bert-base-chinese": dict(vocab_size=21128, hidden_size=768,
                              num_hidden_layers=12, num_attention_heads=12),
    "bert-tiny": dict(vocab_size=512, hidden_size=64, num_hidden_layers=2,
                      num_attention_heads=4, max_position_embeddings=128,
                      hidden_dropout_prob=0.0,
                      attention_probs_dropout_prob=0.0),
}


def _resolve_config(name, **overrides):
    cfg = dict(BERT_CONFIGS[name])
    cfg.update(overrides)
    return BertConfig(**cfg)


def _init_attr(cfg):
    return ParamAttr(initializer=Normal(mean=0.0, std=cfg.initializer_range))


from .modeling_utils import (FromPretrainedMixin,
                             normalize_attention_mask as _normalize_mask)


class BertSelfAttention(Layer):
    """Bidirectional multi-head attention with mp-sharded heads (ref:
    bert/modeling.py's nn.MultiHeadAttention usage)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.cfg = config
        h = config.hidden_size
        wa = _init_attr(config)
        if getattr(config, "fused_qkv", False):
            # one [h, 3h] matmul, Megatron head-interleave [H, 3, d]
            # (same layout/conversion as GPT — gpt.fuse_qkv_state)
            self.qkv_proj = ColumnParallelLinear(h, 3 * h, weight_attr=wa,
                                                 gather_output=False)
        else:
            self.q_proj = ColumnParallelLinear(h, h, weight_attr=wa,
                                               gather_output=False)
            self.k_proj = ColumnParallelLinear(h, h, weight_attr=wa,
                                               gather_output=False)
            self.v_proj = ColumnParallelLinear(h, h, weight_attr=wa,
                                               gather_output=False)
        self.out_proj = RowParallelLinear(h, h, weight_attr=wa,
                                          input_is_parallel=True)

    def _heads(self, x):
        b, s = x.shape[0], x.shape[1]
        return x.reshape([b, s, -1, self.cfg.head_dim])

    def _qkv(self, x):
        if getattr(self.cfg, "fused_qkv", False):
            qkv = self.qkv_proj(x)
            b, s = qkv.shape[0], qkv.shape[1]
            qkv = qkv.reshape([b, s, -1, 3, self.cfg.head_dim])
            return qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]
        return (self._heads(self.q_proj(x)), self._heads(self.k_proj(x)),
                self._heads(self.v_proj(x)))

    def forward(self, x, attn_mask=None):
        q, k, v = self._qkv(x)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.cfg.attention_probs_dropout_prob
            if self.training else 0.0,
            is_causal=False, training=self.training,
            use_flash=self.cfg.use_flash_attention)
        b, s = out.shape[0], out.shape[1]
        return self.out_proj(out.reshape([b, s, -1]))


class BertLayer(Layer):
    """Post-LN encoder block (ref BERT normalize_before=False)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.cfg = config
        eps = config.layer_norm_eps
        wa = _init_attr(config)
        self.attn = BertSelfAttention(config)
        self.dropout1 = Dropout(config.hidden_dropout_prob)
        self.ln_1 = LayerNorm(config.hidden_size, epsilon=eps)
        self.fc1 = ColumnParallelLinear(
            config.hidden_size, config.intermediate_size, weight_attr=wa,
            gather_output=False)
        self.fc2 = RowParallelLinear(
            config.intermediate_size, config.hidden_size, weight_attr=wa,
            input_is_parallel=True)
        self.act = getattr(F, config.hidden_act)
        self.dropout2 = Dropout(config.hidden_dropout_prob)
        self.ln_2 = LayerNorm(config.hidden_size, epsilon=eps)

    def forward(self, x, attn_mask=None):
        h1 = self.dropout1(self.attn(x, attn_mask))
        if getattr(self.cfg, "fused_ln", False):
            # post-LN fuses at BOTH block sites: y = LN(x + h) is the
            # whole pattern; want_sum=False skips even the sum's HBM
            # write (it is not consumed downstream)
            from .modeling_utils import fused_residual_ln
            x = fused_residual_ln(x, h1, self.ln_1, want_sum=False)
            h2 = self.dropout2(self.fc2(self.act(self.fc1(x))))
            x = fused_residual_ln(x, h2, self.ln_2, want_sum=False)
            return x
        x = self.ln_1(x + h1)
        x = self.ln_2(x + self.dropout2(self.fc2(self.act(self.fc1(x)))))
        return x


def _build_encoder(config):
    """LayerList of BertLayer, or the scan-over-layers stack when
    config.scan_layers (checkpoints convert with
    nn.scan_stack.stack_layer_state / unstack_layer_state)."""
    blocks = [BertLayer(config) for _ in range(config.num_hidden_layers)]
    if not config.scan_layers:
        return LayerList(blocks)
    from ..nn.scan_stack import ScannedLayerStack
    return ScannedLayerStack(
        blocks,
        has_dropout=(config.hidden_dropout_prob > 0
                     or config.attention_probs_dropout_prob > 0),
        recompute=getattr(config, "recompute", False))


class BertEmbeddings(Layer):
    """word (vocab-parallel) + position + token-type embeddings with
    post-sum LayerNorm (ref bert/modeling.py BertEmbeddings)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        wa = _init_attr(config)
        self.word_embeddings = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size, weight_attr=wa)
        self.position_embeddings = Embedding(
            config.max_position_embeddings, config.hidden_size,
            weight_attr=wa)
        self.token_type_embeddings = Embedding(
            config.type_vocab_size, config.hidden_size, weight_attr=wa)
        self.layer_norm = LayerNorm(config.hidden_size,
                                    epsilon=config.layer_norm_eps)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = Tensor(jnp.arange(s, dtype=jnp.int32)[None, :])
        if token_type_ids is None:
            token_type_ids = Tensor(
                jnp.zeros((input_ids.shape[0], s), dtype=jnp.int32))
        e = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(e))


class BertPooler(Layer):
    """[CLS] token -> dense -> tanh (ref BertPooler)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.dense = Linear(config.hidden_size, config.hidden_size,
                            weight_attr=_init_attr(config))
        self.act = getattr(F, config.pool_act)

    def forward(self, hidden):
        return self.act(self.dense(hidden[:, 0]))


class BertModel(FromPretrainedMixin, Layer):
    """ref: bert/modeling.py BertModel — returns (sequence_output,
    pooled_output)."""

    def __init__(self, config: BertConfig = None, **kwargs):
        super().__init__()
        if config is None:
            config = BertConfig(**kwargs)
        elif isinstance(config, dict):
            config = BertConfig(**config)
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = _build_encoder(config)
        self.pooler = BertPooler(config)

    @classmethod
    def from_config_name(cls, name, **overrides):
        return cls(_resolve_config(name, **overrides))


    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        mask = _normalize_mask(attention_mask)
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        if self.config.scan_layers:
            x = self.encoder(x, mask)
        else:
            for blk in self.encoder:
                x = blk(x, mask)
        return x, self.pooler(x)


class BertLMPredictionHead(Layer):
    """MLM head: dense + act + LN, decode tied to the word embedding via
    parallel_matmul (ref BertLMPredictionHead's decoder_weight tie)."""

    def __init__(self, config: BertConfig, embedding_weights):
        super().__init__()
        self.transform = Linear(config.hidden_size, config.hidden_size,
                                weight_attr=_init_attr(config))
        self.act = getattr(F, config.hidden_act)
        self.layer_norm = LayerNorm(config.hidden_size,
                                    epsilon=config.layer_norm_eps)
        self._tied = embedding_weights
        from jax.sharding import PartitionSpec as P
        from ..nn.initializer import Constant
        self.decoder_bias = self.create_parameter(
            [config.vocab_size], attr=ParamAttr(initializer=Constant(0.0)),
            is_bias=True)
        # logits from parallel_matmul(gather_output=False) are vocab-LOCAL
        # under mp, so the bias must shard over the same axis
        self.decoder_bias.sharding_spec = P("mp")

    def forward(self, hidden):
        h = self.layer_norm(self.act(self.transform(hidden)))
        logits = parallel_matmul(h, self._tied, transpose_y=True,
                                 gather_output=False)
        return logits + self.decoder_bias


class BertPretrainingHeads(Layer):
    def __init__(self, config: BertConfig, embedding_weights):
        super().__init__()
        self.predictions = BertLMPredictionHead(config, embedding_weights)
        self.seq_relationship = Linear(config.hidden_size, 2,
                                       weight_attr=_init_attr(config))

    def forward(self, sequence_output, pooled_output):
        return (self.predictions(sequence_output),
                self.seq_relationship(pooled_output))


class BertForPretraining(FromPretrainedMixin, Layer):
    """ref: BertForPretraining — MLM + NSP."""

    def __init__(self, config: BertConfig = None, **kwargs):
        super().__init__()
        self.bert = BertModel(config, **kwargs)
        self.config = self.bert.config
        self.cls = BertPretrainingHeads(
            self.config, self.bert.embeddings.word_embeddings.weight)

    @classmethod
    def from_config_name(cls, name, **overrides):
        return cls(_resolve_config(name, **overrides))


    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, position_ids,
                                attention_mask)
        cap = getattr(self.config, "mlm_gather_capacity", 0.0)
        if cap and self.training:
            return _mlm_gather_aux(self.config, self.cls.predictions,
                                   seq,
                                   self.cls.seq_relationship(pooled),
                                   cap)
        return self.cls(seq, pooled)


def _mlm_gather_aux(config, pred_head, seq, nsp_score, cap):
    """Defer the MLM head to the criterion so it can gather the masked
    positions (only the criterion sees the labels). Under a trace this
    carries the head's TRACED parameter values — functional_call
    restores the Parameter objects' values after forward, so passing
    Parameters would bake stale constants into the jit (same contract
    as chunked_ce). EAGERLY it carries the Parameters themselves: a
    fresh Tensor is a detached tape leaf and loss.backward() would
    silently drop every head grad (ADVICE r5 #1)."""
    from ..autograd import in_jax_trace
    t = pred_head.transform
    ln = pred_head.layer_norm

    def val(p):
        if in_jax_trace((p._value,)):
            return Tensor(p._value, stop_gradient=p.stop_gradient)
        return p
    return {
        "_loss_only_aux": True, "mlm_gather": True,
        "hidden": seq, "nsp_score": nsp_score,
        "t_w": val(t.weight), "t_b": val(t.bias),
        "ln_w": val(ln.weight), "ln_b": val(ln.bias),
        "dec_w": val(pred_head._tied), "dec_b": val(pred_head.decoder_bias),
        # static (consumed inside the trace, stripped before jit output)
        "act": config.hidden_act, "capacity": float(cap),
        "ln_eps": config.layer_norm_eps,
    }


class BertPretrainingCriterion(Layer):
    """ref: BertPretrainingCriterion — summed MLM (masked mean) + NSP CE,
    vocab-parallel safe."""

    def __init__(self, config=None):
        super().__init__()
        self.ce = ParallelCrossEntropy()
        # eager-path observability for mlm_gather_capacity: number of
        # masked positions the last _gathered_mlm_loss call CLIPPED
        # (0-dim int Tensor; None before the first eager gathered call).
        # Clipping biases the loss downward, so a nonzero value means
        # the configured capacity is undersized for the data's mask
        # rate (ADVICE r5 #4). Only set outside jit traces.
        self.last_mlm_overflow = None

    def forward(self, prediction_scores, seq_relationship_score=None,
                masked_lm_labels=None, next_sentence_labels=None,
                masked_lm_weights=None):
        if isinstance(prediction_scores, dict) and \
                prediction_scores.get("mlm_gather"):
            # the model returned ONE aux dict instead of (scores, nsp),
            # so every label argument arrives one position early
            return self._gathered_mlm_loss(
                prediction_scores,
                masked_lm_labels=seq_relationship_score,
                next_sentence_labels=masked_lm_labels,
                masked_lm_weights=next_sentence_labels)
        mlm = self.ce(prediction_scores, masked_lm_labels)
        if masked_lm_weights is not None:
            w = masked_lm_weights if isinstance(masked_lm_weights, Tensor) \
                else Tensor(masked_lm_weights)
            w = w.astype(mlm.dtype)
            mlm_loss = (mlm * w).sum() / w.sum().clip(min=1.0)
        else:
            # masked mean: ignore_index positions are zeroed by the CE, so
            # normalise by the valid count, not b*s (ref criterion divides
            # by the masked-token count)
            labels = masked_lm_labels if isinstance(masked_lm_labels, Tensor)\
                else Tensor(masked_lm_labels)
            valid = Tensor(
                (labels._value != self.ce.ignore_index)).astype(mlm.dtype)
            mlm_loss = mlm.sum() / valid.sum().clip(min=1.0)
        if next_sentence_labels is None:
            return mlm_loss
        nsp_loss = F.cross_entropy(seq_relationship_score,
                                   next_sentence_labels)
        return mlm_loss + nsp_loss

    def _gathered_mlm_loss(self, aux, masked_lm_labels,
                           next_sentence_labels=None,
                           masked_lm_weights=None):
        """MLM loss over at most ceil(capacity*B*S) GATHERED masked
        positions: transform+LN+decode run on [K, h] instead of
        [B*S, h] (see BertConfig.mlm_gather_capacity). Equals the full
        loss exactly while the masked count fits K; overflow drops the
        latest excess positions but keeps the full-count normalizer."""
        import math as _math

        import jax as _jax

        from ..autograd import apply_op
        from ..distributed.fleet.mpu import axis_bound
        if axis_bound("mp"):
            raise NotImplementedError(
                "mlm_gather_capacity does not run inside shard_map "
                "tensor parallelism (the decode weight is vocab-local) "
                "— use the default head + ParallelCrossEntropy there")
        import functools as _ft

        # exactness parity with the baseline head: F.gelu defaults to
        # the exact erf form (jax.nn.gelu alone defaults to the tanh
        # approximation — up to ~1e-3 apart at |x|~2)
        acts = {"gelu": _ft.partial(_jax.nn.gelu, approximate=False),
                "relu": _jax.nn.relu, "silu": _jax.nn.silu,
                "swish": _jax.nn.silu, "tanh": jnp.tanh}
        if aux["act"] not in acts:
            raise NotImplementedError(
                f"mlm_gather_capacity with hidden_act="
                f"{aux['act']!r} is not wired (supported: "
                f"{sorted(acts)}); set mlm_gather_capacity=0")
        act = acts[aux["act"]]
        cap = float(aux["capacity"])
        eps = float(aux["ln_eps"])
        ii = self.ce.ignore_index

        def run(hidden, t_w, t_b, ln_w, ln_b, dec_w, dec_b, y, w):
            b, s, h = hidden.shape
            n = b * s
            k = max(8, int(_math.ceil(cap * n)))
            yf = y.reshape(n)
            valid = yf != ii
            # stable argsort: valid positions first, original order kept
            idx = jnp.argsort(jnp.where(valid, 0, 1), stable=True)[:k]
            hg = hidden.reshape(n, h)[idx]
            yg = yf[idx]          # overflow tail is ii -> zero loss
            # AMP parity with the baseline head: operands stay in their
            # (possibly bf16) dtype so the matmuls ride the MXU at full
            # rate; accumulation is fp32 via preferred_element_type
            hh = act(jnp.einsum("kh,ho->ko", hg, t_w,
                                preferred_element_type=jnp.float32)
                     + t_b.astype(jnp.float32))
            mu = jnp.mean(hh, axis=-1, keepdims=True)
            var = jnp.mean(jnp.square(hh - mu), axis=-1, keepdims=True)
            hh = (hh - mu) * _jax.lax.rsqrt(var + eps) \
                * ln_w.astype(jnp.float32) + ln_b.astype(jnp.float32)
            logits = jnp.einsum("kh,vh->kv", hh.astype(hg.dtype), dec_w,
                                preferred_element_type=jnp.float32) \
                + dec_b.astype(jnp.float32)
            lse = _jax.scipy.special.logsumexp(logits, axis=-1)
            safe = jnp.clip(yg.astype(jnp.int32), 0, None)
            picked = jnp.take_along_axis(logits, safe[:, None],
                                         axis=-1)[:, 0]
            ok = yg != ii
            losses = jnp.where(ok, lse - picked, 0.0)
            if w is not None:
                wg = w.reshape(n)[idx].astype(jnp.float32)
                return jnp.sum(losses * wg) / \
                    jnp.clip(jnp.sum(w.astype(jnp.float32)), 1.0)
            count = jnp.sum(valid.astype(jnp.float32))
            return jnp.sum(losses) / jnp.clip(count, 1.0)

        y = masked_lm_labels if isinstance(masked_lm_labels, Tensor) \
            else Tensor(masked_lm_labels)
        # capacity-clip signal (ADVICE r5 #4): masked positions beyond K
        # are dropped from the loss while the normalizer keeps the full
        # count — count them so undersizing is detectable, not silent
        from ..autograd import in_jax_trace
        hid = aux["hidden"]
        n_pos = int(hid.shape[0]) * int(hid.shape[1])
        k_cap = max(8, int(_math.ceil(cap * n_pos)))
        overflow = apply_op(
            lambda yy: jnp.maximum(
                jnp.sum((yy.reshape(-1) != ii).astype(jnp.int32))
                - jnp.int32(k_cap), 0),
            y, differentiable=False)
        if not in_jax_trace((overflow._value,)):
            self.last_mlm_overflow = overflow
        args = [aux["hidden"], aux["t_w"], aux["t_b"], aux["ln_w"],
                aux["ln_b"], aux["dec_w"], aux["dec_b"], y]
        if masked_lm_weights is not None:
            w = masked_lm_weights if isinstance(masked_lm_weights, Tensor)\
                else Tensor(masked_lm_weights)
            mlm_loss = apply_op(lambda *a: run(*a), *args, w)
        else:
            mlm_loss = apply_op(lambda *a: run(*a, None), *args)
        if next_sentence_labels is None:
            return mlm_loss
        nsp_loss = F.cross_entropy(aux["nsp_score"],
                                   next_sentence_labels)
        return mlm_loss + nsp_loss


class _TaskHead(FromPretrainedMixin, Layer):
    """Shared scaffolding for encoder task heads: builds the backbone under
    the reference's attribute name (model.bert / model.ernie) so state-dict
    keys match, and exposes it uniformly as `self.backbone`. ERNIE heads in
    ernie.py subclass these with backbone_cls/backbone_attr/_resolve
    swapped (same relationship the reference's ernie/modeling.py has to
    bert/modeling.py)."""

    backbone_cls = BertModel
    backbone_attr = "bert"
    _resolve = staticmethod(_resolve_config)

    def __init__(self, config=None, **kwargs):
        super().__init__()
        backbone = self.backbone_cls(config, **kwargs)
        setattr(self, self.backbone_attr, backbone)
        self.config = backbone.config

    @property
    def backbone(self):
        return getattr(self, self.backbone_attr)

    @classmethod
    def from_config_name(cls, name, **overrides):
        num_labels = overrides.pop("num_labels", None)
        kw = {} if num_labels is None else {"num_labels": num_labels}
        return cls(cls._resolve(name, **overrides), **kw)



class BertForMaskedLM(_TaskHead):
    def __init__(self, config=None, **kwargs):
        super().__init__(config, **kwargs)
        self.cls = BertLMPredictionHead(
            self.config, self.backbone.embeddings.word_embeddings.weight)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        seq, _ = self.backbone(input_ids, token_type_ids, position_ids,
                               attention_mask)
        return self.cls(seq)


class BertForSequenceClassification(_TaskHead):
    """ref: BertForSequenceClassification — pooled output -> dropout ->
    num_labels logits."""

    def __init__(self, config=None, num_labels=None, **kwargs):
        super().__init__(config, **kwargs)
        n = num_labels or self.config.num_labels
        self.dropout = Dropout(self.config.hidden_dropout_prob)
        self.classifier = Linear(self.config.hidden_size, n,
                                 weight_attr=_init_attr(self.config))

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        _, pooled = self.backbone(input_ids, token_type_ids, position_ids,
                                  attention_mask)
        return self.classifier(self.dropout(pooled))


class BertForTokenClassification(_TaskHead):
    def __init__(self, config=None, num_labels=None, **kwargs):
        super().__init__(config, **kwargs)
        n = num_labels or self.config.num_labels
        self.dropout = Dropout(self.config.hidden_dropout_prob)
        self.classifier = Linear(self.config.hidden_size, n,
                                 weight_attr=_init_attr(self.config))

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        seq, _ = self.backbone(input_ids, token_type_ids, position_ids,
                               attention_mask)
        return self.classifier(self.dropout(seq))


class BertForQuestionAnswering(_TaskHead):
    """ref: BertForQuestionAnswering — (start_logits, end_logits)."""

    def __init__(self, config=None, **kwargs):
        super().__init__(config, **kwargs)
        self.classifier = Linear(self.config.hidden_size, 2,
                                 weight_attr=_init_attr(self.config))

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        seq, _ = self.backbone(input_ids, token_type_ids, position_ids,
                               attention_mask)
        logits = self.classifier(seq)
        return logits[:, :, 0], logits[:, :, 1]
