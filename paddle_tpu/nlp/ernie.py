"""ERNIE encoder family (Baidu's flagship pretrained LM).

ref parity: PaddleNLP paddlenlp/transformers/ernie/modeling.py (ErnieModel,
ErnieForSequenceClassification, ErnieForTokenClassification,
ErnieForQuestionAnswering, ErnieForMaskedLM, ErnieForPretraining,
ErniePretrainingCriterion) and ernie/configuration.py (ERNIE 3.0 configs).

Architecturally ERNIE is a BERT-style post-LN encoder plus an optional
task-type embedding (use_task_id, ERNIE 3.0); we reuse the mesh-aware BERT
blocks and add the task embedding — same relationship the reference has
(ernie/modeling.py mirrors bert/modeling.py with task_type_embeddings).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.layers_common import Dropout, Embedding, Linear
from ..tensor import Tensor
from .bert import (BertConfig, BertEmbeddings,
                   BertLMPredictionHead, BertPooler,
                   BertForMaskedLM, BertForSequenceClassification,
                   BertForTokenClassification, BertForQuestionAnswering,
                   BertPretrainingCriterion, _init_attr, _normalize_mask)
from .modeling_utils import FromPretrainedMixin


@dataclass
class ErnieConfig(BertConfig):
    vocab_size: int = 40000
    task_type_vocab_size: int = 3
    use_task_id: bool = True
    pool_act: str = "tanh"


# ref: ernie/configuration.py ERNIE_PRETRAINED_INIT_CONFIGURATION
# (ernie-3.0-base-zh: 12L x 768; ernie-3.0-medium-zh: 6L x 768)
ERNIE_CONFIGS = {
    "ernie-3.0-base-zh": dict(vocab_size=40000, hidden_size=768,
                              num_hidden_layers=12, num_attention_heads=12,
                              max_position_embeddings=2048),
    "ernie-3.0-medium-zh": dict(vocab_size=40000, hidden_size=768,
                                num_hidden_layers=6, num_attention_heads=12,
                                max_position_embeddings=2048),
    "ernie-3.0-mini-zh": dict(vocab_size=40000, hidden_size=384,
                              num_hidden_layers=6, num_attention_heads=12,
                              max_position_embeddings=2048),
    "ernie-1.0": dict(vocab_size=18000, hidden_size=768,
                      num_hidden_layers=12, num_attention_heads=12,
                      max_position_embeddings=513, use_task_id=False),
    "ernie-tiny": dict(vocab_size=512, hidden_size=64, num_hidden_layers=2,
                       num_attention_heads=4, max_position_embeddings=128,
                       hidden_dropout_prob=0.0,
                       attention_probs_dropout_prob=0.0),
}


def _resolve_config(name, **overrides):
    cfg = dict(ERNIE_CONFIGS[name])
    cfg.update(overrides)
    return ErnieConfig(**cfg)


class ErnieEmbeddings(BertEmbeddings):
    """BertEmbeddings + task-type embedding (ref ErnieEmbeddings)."""

    def __init__(self, config: ErnieConfig):
        super().__init__(config)
        self.use_task_id = config.use_task_id
        if config.use_task_id:
            self.task_type_embeddings = Embedding(
                config.task_type_vocab_size, config.hidden_size,
                weight_attr=_init_attr(config))

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                task_type_ids=None):
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = Tensor(jnp.arange(s, dtype=jnp.int32)[None, :])
        if token_type_ids is None:
            token_type_ids = Tensor(
                jnp.zeros((input_ids.shape[0], s), dtype=jnp.int32))
        e = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        if self.use_task_id:
            if task_type_ids is None:
                task_type_ids = Tensor(
                    jnp.zeros((input_ids.shape[0], s), dtype=jnp.int32))
            e = e + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.layer_norm(e))


class ErnieModel(FromPretrainedMixin, Layer):
    """ref: ernie/modeling.py ErnieModel — returns (sequence_output,
    pooled_output)."""

    def __init__(self, config: ErnieConfig = None, **kwargs):
        super().__init__()
        if config is None:
            config = ErnieConfig(**kwargs)
        elif isinstance(config, dict):
            config = ErnieConfig(**config)
        self.config = config
        self.embeddings = ErnieEmbeddings(config)
        from .bert import _build_encoder
        self.encoder = _build_encoder(config)
        self.pooler = BertPooler(config)

    @classmethod
    def from_config_name(cls, name, **overrides):
        return cls(_resolve_config(name, **overrides))


    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        mask = _normalize_mask(attention_mask)
        x = self.embeddings(input_ids, token_type_ids, position_ids,
                            task_type_ids)
        if self.config.scan_layers:
            x = self.encoder(x, mask)
        else:
            for blk in self.encoder:
                x = blk(x, mask)
        return x, self.pooler(x)


class ErnieForSequenceClassification(BertForSequenceClassification):
    backbone_cls = ErnieModel
    backbone_attr = "ernie"
    _resolve = staticmethod(_resolve_config)


class ErnieForTokenClassification(BertForTokenClassification):
    backbone_cls = ErnieModel
    backbone_attr = "ernie"
    _resolve = staticmethod(_resolve_config)


class ErnieForQuestionAnswering(BertForQuestionAnswering):
    backbone_cls = ErnieModel
    backbone_attr = "ernie"
    _resolve = staticmethod(_resolve_config)


class ErnieForMaskedLM(BertForMaskedLM):
    backbone_cls = ErnieModel
    backbone_attr = "ernie"
    _resolve = staticmethod(_resolve_config)


class ErnieForPretraining(Layer):
    """ref: ErnieForPretraining — MLM + NSP heads."""

    def __init__(self, config: ErnieConfig = None, **kwargs):
        super().__init__()
        self.ernie = ErnieModel(config, **kwargs)
        self.config = self.ernie.config
        self.cls = BertLMPredictionHead(
            self.config, self.ernie.embeddings.word_embeddings.weight)
        self.seq_relationship = Linear(self.config.hidden_size, 2,
                                       weight_attr=_init_attr(self.config))

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        seq, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                                 attention_mask)
        cap = getattr(self.config, "mlm_gather_capacity", 0.0)
        if cap and self.training:
            from .bert import _mlm_gather_aux
            return _mlm_gather_aux(self.config, self.cls, seq,
                                   self.seq_relationship(pooled), cap)
        return self.cls(seq), self.seq_relationship(pooled)


class ErniePretrainingCriterion(BertPretrainingCriterion):
    """ref: ErniePretrainingCriterion — same contract as BERT's."""
