"""Llama-family models, TPU-native.

ref parity: paddlenlp/transformers/llama/modeling.py (LlamaModel /
LlamaForCausalLM: RMSNorm pre-norm blocks, rotary position embeddings,
grouped-query attention, SwiGLU MLP, untied-or-tied LM head). The
reference runs CUDA fused rope/rms kernels and fleet mp; here the
whole step compiles through XLA with the same TPU levers as GPT:
GSPMD tensor parallelism (Column/RowParallelLinear specs), flash
attention (Pallas), scan-over-layers, remat, sequence parallelism,
and the fused chunked head+CE (the [N, vocab] logits never
materialize). RoPE cos/sin are computed in-trace from positions —
no table buffers, so the cached-decode path (positions = cache_index
+ arange) stays a single compiled program (nlp/generation.py's static
cache/cache_index contract, shared with GPT).

Numerics are pinned against torch/transformers' LlamaForCausalLM in
tests/test_llama.py (same half-split rotate convention).
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..nn.layer import Layer
from ..nn.initializer import Normal, ParamAttr
from ..nn.layers_common import LayerList
from ..nn.layers_norm import RMSNorm
from ..tensor import Tensor
from ..distributed.fleet.mpu import (ColumnParallelLinear,
                                     RowParallelLinear,
                                     VocabParallelEmbedding,
                                     parallel_matmul)
from .modeling_utils import FromPretrainedMixin, normalize_attention_mask
from .gpt import GPTPretrainingCriterion
import paddle_tpu.nn.functional as F

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM",
           "LlamaPretrainingCriterion", "LLAMA_CONFIGS"]


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    # grouped-query attention: kv heads < heads (0 -> = heads)
    num_key_value_heads: int = 0
    intermediate_size: int = 0  # 0 -> the Llama 8/3*h rounded to 256
    max_position_embeddings: int = 2048
    initializer_range: float = 0.02
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_flash_attention: bool = True
    recompute: bool = False
    scan_layers: bool = False
    sequence_parallel: str = ""
    chunked_ce: int = 0

    def __post_init__(self):
        if not self.num_key_value_heads:
            self.num_key_value_heads = self.num_attention_heads
        if self.num_attention_heads % self.num_key_value_heads:
            raise ValueError(
                f"heads ({self.num_attention_heads}) must be a multiple "
                f"of num_key_value_heads ({self.num_key_value_heads})")
        if not self.intermediate_size:
            m = int(8 * self.hidden_size / 3)
            self.intermediate_size = (m + 255) // 256 * 256
        if self.sequence_parallel not in ("", "ring", "ulysses"):
            raise ValueError(
                f"sequence_parallel={self.sequence_parallel!r}")

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


# ref: llama/configuration.py pretrained configs (paddlenlp model zoo)
LLAMA_CONFIGS = {
    "llama-7b": dict(hidden_size=4096, num_hidden_layers=32,
                     num_attention_heads=32, intermediate_size=11008),
    "llama2-7b": dict(hidden_size=4096, num_hidden_layers=32,
                      num_attention_heads=32, intermediate_size=11008,
                      max_position_embeddings=4096),
    "llama3-8b": dict(vocab_size=128256, hidden_size=4096,
                      num_hidden_layers=32, num_attention_heads=32,
                      num_key_value_heads=8, intermediate_size=14336,
                      max_position_embeddings=8192,
                      rope_theta=500000.0),
    "llama-tiny": dict(vocab_size=256, hidden_size=64,
                       num_hidden_layers=2, num_attention_heads=4,
                       num_key_value_heads=2, intermediate_size=128,
                       max_position_embeddings=128),
    # single-chip bench flagship for the GQA family: a TinyLlama-class
    # 1.1B shape (GQA 4:16); like gpt3-1.3B it needs bf16 Adam moments
    # + remat to fit one 16GB chip (bench.py worker_llama defaults)
    "llama-1b": dict(vocab_size=32000, hidden_size=2048,
                     num_hidden_layers=22, num_attention_heads=16,
                     num_key_value_heads=4, intermediate_size=5632,
                     max_position_embeddings=2048),
}


def _resolve_config(name, **overrides):
    cfg = dict(LLAMA_CONFIGS[name])
    cfg.update(overrides)
    return LlamaConfig(**cfg)


def _init_attr(cfg):
    return ParamAttr(initializer=Normal(mean=0.0,
                                        std=cfg.initializer_range))


def apply_rope(x, positions, theta):
    """Rotary embedding, HF/paddlenlp half-split convention:
    x [B, S, H, D]; positions [S] (absolute, shared across the batch)
    or [B, S] (per-row — the paged serving decode, where every slot
    sits at its own offset). rotate_half(x) = concat(-x2, x1) over the
    last-dim halves; out = x*cos + rot*sin with cos/sin of
    freqs = pos * theta^(-2i/D) repeated over halves. Computed
    in-trace (no tables) so cached decode's dynamic offset (positions
    = cache_index + arange) compiles into the one decode program."""
    d = x.shape[-1]
    inv = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    freqs = positions.astype(jnp.float32)[..., None] * inv  # [..., D/2]
    cos = jnp.concatenate([jnp.cos(freqs), jnp.cos(freqs)], axis=-1)
    sin = jnp.concatenate([jnp.sin(freqs), jnp.sin(freqs)], axis=-1)
    if positions.ndim == 1:      # [S] -> broadcast over batch + heads
        cos, sin = cos[None, :, None, :], sin[None, :, None, :]
    else:                        # [B, S] -> broadcast over heads
        cos, sin = cos[:, :, None, :], sin[:, :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    rot = jnp.concatenate([-x2, x1], axis=-1)
    return (x.astype(jnp.float32) * cos
            + rot.astype(jnp.float32) * sin).astype(x.dtype)


def _repeat_kv(x, n):
    """[B, S, Hkv, D] -> [B, S, Hkv*n, D] (GQA share): each kv head
    serves n query heads, laid out so query head h reads kv head
    h // n — matching HF/paddlenlp repeat_kv."""
    if n == 1:
        return x
    b, s, hkv, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :],
                            (b, s, hkv, n, d)).reshape(b, s, hkv * n, d)


class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.cfg = config
        h = config.hidden_size
        kvh = config.num_key_value_heads * config.head_dim
        wa = _init_attr(config)
        self.q_proj = ColumnParallelLinear(h, h, weight_attr=wa,
                                           has_bias=False,
                                           gather_output=False)
        self.k_proj = ColumnParallelLinear(h, kvh, weight_attr=wa,
                                           has_bias=False,
                                           gather_output=False)
        self.v_proj = ColumnParallelLinear(h, kvh, weight_attr=wa,
                                           has_bias=False,
                                           gather_output=False)
        self.o_proj = RowParallelLinear(h, h, weight_attr=wa,
                                        has_bias=False,
                                        input_is_parallel=True)

    def _shaped_qkv(self, x):
        b, s = x.shape[0], x.shape[1]
        d = self.cfg.head_dim
        q = self.q_proj(x).reshape([b, s, -1, d])
        k = self.k_proj(x).reshape([b, s, -1, d])
        v = self.v_proj(x).reshape([b, s, -1, d])
        return q, k, v

    def forward(self, x, attn_mask=None, cache=None, cache_index=None):
        from ..autograd import apply_op
        cfg = self.cfg
        groups = cfg.num_attention_heads // cfg.num_key_value_heads
        if cache_index is not None and cache is None:
            raise ValueError(
                "cache_index was given without cache: the static-cache "
                "decode path updates preallocated [B, S_max, Hkv, D] "
                "buffers in place — build them first (generation.py's "
                "init_cache / forward(use_cache=True)) or drop "
                "cache_index")
        q, k, v = self._shaped_qkv(x)
        from .paged_cache import PagedLayerCache, paged_layer_forward
        if isinstance(cache, PagedLayerCache):
            # serving path (nlp/serving.py): the shared paged contract
            # handles per-slot RoPE + page write + GQA attention
            return paged_layer_forward(q, k, v, cache, self.o_proj,
                                       groups=groups,
                                       rope_theta=cfg.rope_theta)
        if cache_index is not None:
            return self._forward_static_cache(q, k, v, cache,
                                              cache_index, groups)
        s = q.shape[1]
        # eager cache continuation: positions offset by the prefix
        # length (concrete at trace — this is the eager parity path;
        # jit decode goes through _forward_static_cache)
        offset = cache[0].shape[1] if cache is not None else 0
        rope = lambda t, p: apply_rope(t, p, cfg.rope_theta)
        pos = offset + jnp.arange(s, dtype=jnp.int32)
        q = apply_op(rope, q, Tensor(pos))
        k = apply_op(rope, k, Tensor(pos))
        if cache is not None:
            if cache[0].shape[1]:
                from ..tensor_ops.manip import concat
                k = concat([cache[0], k], axis=1)
                v = concat([cache[1], v], axis=1)
            cache = (k, v)
        kr = apply_op(_repeat_kv, k, n=groups)
        vr = apply_op(_repeat_kv, v, n=groups)
        sp_out = None if cache is not None else \
            self._maybe_sp(q, kr, vr, attn_mask)
        if sp_out is not None:
            out = sp_out
        else:
            out = F.scaled_dot_product_attention(
                q, kr, vr, attn_mask=attn_mask, is_causal=True,
                training=self.training,
                use_flash=cfg.use_flash_attention)
        b, so = out.shape[0], out.shape[1]
        out = self.o_proj(out.reshape([b, so, -1]))
        return (out, cache) if cache is not None else out

    def _maybe_sp(self, q, k, v, attn_mask):
        """Training/no-cache path only: cached decode grows S
        dynamically (rectangular q/k), which a static sequence shard
        cannot host — same contract as GPT's _maybe_sequence_parallel
        (the caller guards cache is None)."""
        mode = self.cfg.sequence_parallel
        if not mode:
            return None
        from ..distributed.mesh import get_mesh
        mesh = get_mesh()
        if mesh is None or "sp" not in mesh.axis_names or \
                mesh.shape["sp"] <= 1:
            return None
        if attn_mask is not None:
            raise ValueError("sequence_parallel attention takes no "
                             "padding mask (mask the loss instead)")
        from ..autograd import apply_op
        from ..distributed.fleet.sequence_parallel import (
            ring_attention_spmd, ulysses_attention_spmd)
        fn = (ring_attention_spmd if mode == "ring"
              else ulysses_attention_spmd)
        return apply_op(
            lambda qq, kk, vv: fn(qq, kk, vv, mesh, causal=True),
            q, k, v)

    def _forward_static_cache(self, q, k, v, cache, cache_index, groups):
        """jit decode fast path: fixed [B, S_max, Hkv, D] buffers
        updated in place at cache_index; RoPE positions offset by the
        index (one compiled program decodes every token). GQA attends
        with a GROUPED einsum against the kv-head buffers directly —
        the repeated [B, S_max, H_full, D] tensors the naive repeat_kv
        materializes per step never exist (that repeat would negate the
        GQA cache saving at decode time)."""
        from ..autograd import apply_op
        theta = self.cfg.rope_theta

        def run(qv, kv, vv, kbuf, vbuf, idx):
            idx = jnp.asarray(idx, jnp.int32)
            s = qv.shape[1]
            pos = idx + jnp.arange(s, dtype=jnp.int32)
            qv = apply_rope(qv, pos, theta)
            kv = apply_rope(kv, pos, theta)
            zero = jnp.int32(0)
            kbuf = jax.lax.dynamic_update_slice(
                kbuf, kv.astype(kbuf.dtype), (zero, idx, zero, zero))
            vbuf = jax.lax.dynamic_update_slice(
                vbuf, vv.astype(vbuf.dtype), (zero, idx, zero, zero))
            b, sq, h, d = qv.shape
            s_max = kbuf.shape[1]
            scale = 1.0 / math.sqrt(d)
            if groups == 1 and sq == 1:
                # single-token MHA decode: valid-length masked kernel
                # (env-gated Pallas on TPU, jnp fallback) — same route
                # as GPT's static-cache fast path
                from ..ops.attention import flash_decode
                lens = jnp.broadcast_to(idx + 1, (b,))
                o = flash_decode(qv.astype(kbuf.dtype), kbuf, vbuf,
                                 lens).astype(qv.dtype)
                return o, kbuf, vbuf
            qg = qv.reshape(b, sq, h // groups, groups, d)
            logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg,
                                kbuf.astype(qv.dtype),
                                preferred_element_type=jnp.float32)
            logits = logits * scale
            # causal vs the WRITTEN prefix: key j visible iff j <= idx+i
            kpos = jnp.arange(s_max)[None, None, None, None, :]
            qpos = (idx + jnp.arange(sq))[None, None, None, :, None]
            logits = jnp.where(kpos <= qpos, logits, -1e30)
            p = jax.nn.softmax(logits, axis=-1).astype(qv.dtype)
            o = jnp.einsum("bhgqk,bkhd->bqhgd", p, vbuf.astype(qv.dtype))
            return o.reshape(b, sq, h, d), kbuf, vbuf

        out, kbuf, vbuf = apply_op(
            run, q, k, v, cache[0], cache[1],
            cache_index if isinstance(cache_index, Tensor)
            else Tensor(jnp.asarray(cache_index)))
        b, s = out.shape[0], out.shape[1]
        out = self.o_proj(out.reshape([b, s, -1]))
        return out, (kbuf, vbuf)


class LlamaMLP(Layer):
    """SwiGLU (ref LlamaMLP): down(silu(gate(x)) * up(x))."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        wa = _init_attr(config)
        h, i = config.hidden_size, config.intermediate_size
        self.gate_proj = ColumnParallelLinear(h, i, weight_attr=wa,
                                              has_bias=False,
                                              gather_output=False)
        self.up_proj = ColumnParallelLinear(h, i, weight_attr=wa,
                                            has_bias=False,
                                            gather_output=False)
        self.down_proj = RowParallelLinear(i, h, weight_attr=wa,
                                           has_bias=False,
                                           input_is_parallel=True)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.cfg = config
        self.input_layernorm = RMSNorm(config.hidden_size,
                                       epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = RMSNorm(
            config.hidden_size, epsilon=config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, attn_mask=None, cache=None, cache_index=None):
        h = self.input_layernorm(x)
        if cache is not None or cache_index is not None:
            h, cache = self.self_attn(h, attn_mask, cache,
                                      cache_index=cache_index)
        else:
            h = self.self_attn(h, attn_mask)
        x = x + h
        x = x + self.mlp(self.post_attention_layernorm(x))
        return (x, cache) if (cache is not None) else x


def _build_layers(config):
    blocks = [LlamaDecoderLayer(config)
              for _ in range(config.num_hidden_layers)]
    if not config.scan_layers:
        return LayerList(blocks)
    from ..nn.scan_stack import ScannedLayerStack
    return ScannedLayerStack(blocks, has_dropout=False,
                             recompute=config.recompute)


class LlamaModel(FromPretrainedMixin, Layer):
    """ref: llama/modeling.py LlamaModel."""

    def __init__(self, config: LlamaConfig = None, **kwargs):
        super().__init__()
        if config is None:
            config = LlamaConfig(**kwargs)
        elif isinstance(config, dict):
            config = LlamaConfig(**config)
        self.config = config
        self.embed_tokens = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size,
            weight_attr=_init_attr(config))
        self.layers = _build_layers(config)
        self.norm = RMSNorm(config.hidden_size,
                            epsilon=config.rms_norm_eps)

    @classmethod
    def from_config_name(cls, name, **overrides):
        return cls(_resolve_config(name, **overrides))

    def forward(self, input_ids, attention_mask=None, use_cache=False,
                cache=None, cache_index=None):
        from .gpt import _recompute_block
        if cache_index is not None and cache is None:
            raise ValueError(
                "cache_index was given without cache: decode-by-index "
                "needs the preallocated static KV buffers (run a "
                "use_cache=True prefill / generation.init_cache first, "
                "or drop cache_index)")
        mask = normalize_attention_mask(attention_mask)
        x = self.embed_tokens(input_ids)
        if self.config.scan_layers:
            if use_cache or cache is not None or cache_index is not None:
                raise NotImplementedError(
                    "scan_layers=True serves training/no-cache forward "
                    "only; build with scan_layers=False for cached "
                    "decode (stack_layer_state converts checkpoints)")
            x = self.layers(x, mask)
            return self.norm(x)
        if use_cache and cache is None:
            cache = [(Tensor(jnp.zeros(
                (x.shape[0], 0, self.config.num_key_value_heads,
                 self.config.head_dim), jnp.float32)),) * 2
                for _ in range(self.config.num_hidden_layers)]
        new_caches = [] if (cache is not None) else None
        for i, blk in enumerate(self.layers):
            if cache is not None or cache_index is not None:
                layer_cache = cache[i] if cache is not None else None
                x, c = blk(x, mask, layer_cache, cache_index=cache_index)
                new_caches.append(c)
            elif self.config.recompute and self.training:
                x = _recompute_block(blk, x, mask)
            else:
                x = blk(x, mask)
        x = self.norm(x)
        return (x, new_caches) if new_caches is not None else x


class LlamaPretrainingCriterion(GPTPretrainingCriterion):
    """ref: llama/modeling.py LlamaPretrainingCriterion — same masked
    CLM cross entropy (and the same fused chunked head+CE contract)."""


class LlamaForCausalLM(FromPretrainedMixin, Layer):
    """ref: llama/modeling.py LlamaForCausalLM (untied lm_head by
    default; tie_word_embeddings=True reuses the embedding)."""

    def __init__(self, config: LlamaConfig = None, **kwargs):
        super().__init__()
        self.llama = LlamaModel(config, **kwargs)
        self.config = self.llama.config
        if not self.config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(
                self.config.hidden_size, self.config.vocab_size,
                weight_attr=_init_attr(self.config), has_bias=False,
                gather_output=False)

    @classmethod
    def from_config_name(cls, name, **overrides):
        return cls(_resolve_config(name, **overrides))

    def _head_weight(self):
        if self.config.tie_word_embeddings:
            return self.llama.embed_tokens.weight, True
        return self.lm_head.weight, False

    def forward(self, input_ids, attention_mask=None, use_cache=False,
                cache=None, cache_index=None):
        out = self.llama(input_ids, attention_mask, use_cache=use_cache,
                         cache=cache, cache_index=cache_index)
        if use_cache or cache is not None or cache_index is not None:
            hidden, new_cache = out
        else:
            hidden, new_cache = out, None
        if (getattr(self.config, "chunked_ce", 0) and self.training
                and new_cache is None):
            w, tied = self._head_weight()
            # the criterion's chunked einsum wants [vocab, hidden]; the
            # untied lm_head stores the Linear [in, out] layout — hand
            # it the TRANSPOSE (a layout op XLA folds into the
            # per-chunk matmul, not a copy). Under a trace use the
            # traced value, not the Parameter (functional_call restores
            # _value post-forward — the Parameter would bake a stale
            # constant); EAGERLY pass the Parameter / a tape-linked
            # transpose, else loss.backward() drops the head grad on a
            # detached leaf (ADVICE r5 #1).
            from ..autograd import in_jax_trace
            if in_jax_trace((w._value,)):
                wv = w._value if tied else w._value.T
                lm_w = Tensor(wv, stop_gradient=w.stop_gradient)
            else:
                lm_w = w if tied else w.transpose([1, 0])
            return {"_loss_only_aux": True, "hidden": hidden,
                    "lm_weight": lm_w,
                    "chunked_ce": int(self.config.chunked_ce)}
        w, tied = self._head_weight()
        if tied:
            logits = parallel_matmul(hidden, w, transpose_y=True,
                                     gather_output=False)
        else:
            # lm_head weight is [in, out] — the Linear layout
            logits = self.lm_head(hidden)
        if new_cache is not None:
            return logits, new_cache
        return logits

    def generate(self, input_ids, **kwargs):
        from .generation import generate as _generate
        return _generate(self, input_ids, **kwargs)
