"""paddle.signal parity (ref: python/paddle/signal.py): frame, overlap_add,
stft, istft.

TPU-native framing: `frame` is a gather over a static index grid (no
dynamic slicing in a Python loop), so stft lowers to one batched FFT —
the whole pipeline jits and differentiates.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .autograd import apply_op
from .tensor import Tensor, to_tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _frame(a, frame_length, hop_length, axis=-1):
    if axis not in (-1, a.ndim - 1, 0):
        raise ValueError("frame: axis must be 0 or -1")
    # axis=0 always selects the [num_frames, frame_length, ...] layout,
    # including for 1-D input where axis 0 is also the last axis
    seq_last = axis == -1 or (axis == a.ndim - 1 and axis != 0)
    if not seq_last:
        a = jnp.moveaxis(a, 0, -1)
    n = a.shape[-1]
    num = 1 + (n - frame_length) // hop_length
    idx = (np.arange(frame_length)[None, :]
           + hop_length * np.arange(num)[:, None])  # [num, frame_length]
    out = a[..., idx]                                # [..., num, fl]
    out = jnp.swapaxes(out, -1, -2)                  # [..., fl, num]
    if not seq_last:
        # reference layout for axis=0: [num_frames, frame_length, ...]
        out = jnp.moveaxis(out, (-1, -2), (0, 1))
    return out


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """ref: paddle.signal.frame — [..., frame_length, num_frames] for
    axis=-1, [num_frames, frame_length, ...] for axis=0."""
    return apply_op(
        lambda a: _frame(a, int(frame_length), int(hop_length), axis), _t(x))


def _overlap_add(a, hop_length, axis=-1):
    seq_last = axis in (-1, a.ndim - 1)
    if not seq_last:
        # [num, fl, ...] -> [..., fl, num]
        a = jnp.moveaxis(a, (0, 1), (-1, -2))
    fl = a.shape[-2]
    num = a.shape[-1]
    n_out = fl + hop_length * (num - 1)
    # scatter-add each frame at its offset: one_hot matmul keeps it static
    # and MXU-friendly for the typical fl<=1024
    frames = jnp.swapaxes(a, -1, -2)  # [..., num, fl]
    idx = (np.arange(fl)[None, :]
           + hop_length * np.arange(num)[:, None])  # [num, fl]
    flat = frames.reshape(frames.shape[:-2] + (num * fl,))
    out = jnp.zeros(frames.shape[:-2] + (n_out,), dtype=a.dtype)
    out = out.at[..., idx.reshape(-1)].add(flat)
    if not seq_last:
        out = jnp.moveaxis(out, -1, 0)
    return out


def overlap_add(x, hop_length, axis=-1, name=None):
    """ref: paddle.signal.overlap_add."""
    return apply_op(lambda a: _overlap_add(a, int(hop_length), axis), _t(x))


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """ref: paddle.signal.stft — input [B, T] (or [T]), output
    [B, n_fft//2+1 (or n_fft), num_frames], complex."""
    n_fft = int(n_fft)
    hop_length = int(hop_length) if hop_length else n_fft // 4
    win_length = int(win_length) if win_length else n_fft
    if window is not None:
        w = _t(window)._value.astype(jnp.float32)
    else:
        w = jnp.ones((win_length,), jnp.float32)
    # center-pad the window to n_fft like the reference
    if win_length < n_fft:
        lp = (n_fft - win_length) // 2
        w = jnp.pad(w, (lp, n_fft - win_length - lp))

    def f(a):
        squeeze = a.ndim == 1
        if squeeze:
            a = a[None]
        if center:
            a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(n_fft // 2,) * 2],
                        mode=pad_mode)
        fr = _frame(a, n_fft, hop_length)            # [B, n_fft, num]
        fr = fr * w[:, None]
        if onesided:
            spec = jnp.fft.rfft(fr, axis=-2)
        else:
            spec = jnp.fft.fft(fr, axis=-2)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return spec[0] if squeeze else spec

    return apply_op(f, _t(x))


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """ref: paddle.signal.istft — least-squares inverse with window
    normalization (NOLA)."""
    n_fft = int(n_fft)
    hop_length = int(hop_length) if hop_length else n_fft // 4
    win_length = int(win_length) if win_length else n_fft
    if window is not None:
        w = _t(window)._value.astype(jnp.float32)
    else:
        w = jnp.ones((win_length,), jnp.float32)
    if win_length < n_fft:
        lp = (n_fft - win_length) // 2
        w = jnp.pad(w, (lp, n_fft - win_length - lp))

    if return_complex and onesided:
        raise ValueError(
            "istft: return_complex=True requires onesided=False "
            "(a onesided spectrum reconstructs a real signal)")

    def f(spec):
        squeeze = spec.ndim == 2
        if squeeze:
            spec = spec[None]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            fr = jnp.fft.irfft(spec, n=n_fft, axis=-2)
        elif return_complex:
            fr = jnp.fft.ifft(spec, axis=-2)
        else:
            fr = jnp.fft.ifft(spec, axis=-2).real
        fr = fr * w[:, None]
        out = _overlap_add(fr, hop_length)
        # NOLA normalization: overlap-added squared window
        wsq = jnp.broadcast_to((w ** 2)[:, None], (n_fft, spec.shape[-1]))
        denom = _overlap_add(wsq, hop_length)
        out = out / jnp.maximum(denom, jnp.finfo(jnp.float32).tiny)
        if center:
            out = out[..., n_fft // 2:]
            tail = out.shape[-1] - n_fft // 2
            out = out[..., :tail]
        if length is not None:
            out = out[..., :length]
        return out[0] if squeeze else out

    return apply_op(f, _t(x))
