"""Process-isolated serving replica — the fleet's unit, for real.

Rounds 11-13 proved the fleet contracts against ``InprocReplica``,
whose transport verbs were deliberately subprocess-shaped but never
crossed a process boundary. ``ProcReplica`` closes that gap: one
ServingEngine runs in a REAL OS subprocess (``proc_child.py``) and the
router-facing object here is a pure transport shim speaking a
length-prefixed, checksummed JSONL protocol over the child's
stdin/stdout pipes — the exact framing discipline of the write-ahead
journal (``<len:8hex> <crc32:8hex> <compact-json>\\n``), so a frame
torn by a SIGKILL mid-write is detected by checksum and dropped, never
misparsed.

Wire frames (child protocol in ``proc_child.py``):

========== ================================================================
direction  frames
========== ================================================================
parent →   ``submit`` / ``cancel`` (the request plane), ``drain``
child  →   ``hello`` (boot complete: pid, warmed flag, compile counts),
           ``hb`` (the health/metrics snapshot a real deployment scrapes
           off the replica's ``/metrics``+``/healthz`` endpoint),
           ``result`` (finished request), ``progress`` (streaming partial
           tokens — how the failover path knows a dead child's in-flight
           state), ``bye`` (clean drain/shutdown)
========== ================================================================

Transport semantics match ``InprocReplica`` verb for verb:

- ``enqueue``/``pop_results``/``ack``: submits are idempotent by fleet
  rid at the child; results are retained parent-side until acked
  (at-least-once) and stamped with the child's **incarnation** so a
  stale leg from a previous incarnation can never pass the router's
  guard;
- ``scrape()``: the last heartbeat snapshot, stamped with its parent-
  side arrival time (staleness = "when did we last hear from the
  process", which is also what detects a wedged child);
- ``kill()`` is a real ``SIGKILL``; ``export_inflight()`` reads the
  parent-side mirror built from ``progress`` frames — the carcass of a
  kill -9'd child cannot be asked, so the facts arrive over the
  streaming token channel BEFORE the crash, exactly as the round-11
  docstrings promised;
- ``respawn()`` (the ``rejoin()`` of a process replica) starts a fresh
  incarnation. The new child warm-boots — ``ServingEngine.warmup()``
  pre-traces the prefill buckets + decode program before the hello —
  so it accepts traffic serving-ready and its compile counts FREEZE
  from the first real wave (the zero-recompile assertion survives
  replacement; the warmup compiles are the one-time boot budget).

Write failures against a dead/full pipe surface as
``faults.TransientError`` so the ``ReplicaClient`` seeded-jitter retry
ladder owns the retry policy (one retry discipline for the whole
transport, in-process or not); reads are torn-frame-tolerant via
``FrameReader`` and the child's stdin reader retries transient EOF on
its own seeded backoff before concluding the parent is gone.

Lifecycle chaos is REAL here — ``os.kill(rep.pid, SIGKILL)`` mid-
decode, SIGTERM drain, exit-at-boot — with two boot-time fault seams
(``replica_exit_at_boot`` / ``replica_slow_boot``, stepped by
incarnation via the child's ``PADDLE_TPU_PROC_FAULTS`` env) driving
the crash-loop and slow-boot drills deterministically. The
``FleetSupervisor`` (supervisor.py) owns detection and respawn policy.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

from ..resilience import faults
from .journal import _frame, _parse_line

__all__ = ["FrameReader", "ProcReplica"]


class FrameReader:
    """Incremental, torn-tolerant decoder for the pipe wire format.

    Feed it arbitrary byte chunks; it yields each complete, checksum-
    valid record exactly once. A frame whose newline has not arrived
    yet is HELD (completed by a later feed, never dropped); a
    newline-terminated line that is short, fails its length or crc, or
    does not parse is dropped and counted in ``dropped`` — the reader
    resyncs at the next newline. This is what makes a SIGKILL mid-
    write (or injected garbage) cost at most the record being written.
    """

    def __init__(self):
        self._buf = b""
        self.dropped = 0
        self.records = 0

    def feed(self, data):
        """Consume `data`; return the list of decoded record dicts."""
        if data:
            self._buf += data
        out = []
        while True:
            i = self._buf.find(b"\n")
            if i < 0:
                return out
            line, self._buf = self._buf[:i], self._buf[i + 1:]
            if not line:
                continue
            rec = _parse_line(line)
            if rec is None:
                self.dropped += 1
                continue
            self.records += 1
            out.append(rec)

    @property
    def pending_bytes(self):
        """Bytes of a not-yet-terminated frame held in the buffer."""
        return len(self._buf)


def _default_flight_base():
    return (os.environ.get("PADDLE_TPU_FLIGHT_DIR")
            or os.environ.get("BENCH_TELEMETRY_DIR")
            or os.path.join(tempfile.gettempdir(), "paddle_tpu_flight"))


class ProcReplica:
    """One ServingEngine in a real OS subprocess, behind the same
    transport verbs as ``InprocReplica``.

    name: replica identity (routing labels, fault targeting).
    spec: the child's engine recipe — JSON-able dict:
        ``builder``: ``"module:function"`` or ``{"path": <abs .py>,
            "fn": <name>}`` returning a ServingEngine;
        ``kwargs``: builder keyword args;
        ``warmup``: prompt lengths / bucket sizes to pre-trace at boot
            (``ServingEngine.warmup``) — the warm-boot contract. The
            decode program is ALWAYS pre-traced (even with no buckets
            listed); pass ``False`` to skip warm boot entirely — the
            heartbeat then honestly reports ``warmed: false`` and a
            supervisor's boot gate will not admit the replica;
        ``aot_dir``: AOT serving-artifact store root
            (``jit.serving_artifact.warm_boot``) — incarnation 1
            traces and exports, respawns restore serialized programs
            and pass the boot gate in seconds; any torn/stale/corrupt
            artifact falls back loudly to the traced path
            (``serve_aot_fallback_total{reason}``), never a wrong
            program. Heartbeats carry ``boot`` (mode aot/traced +
            wall) — ``fleet_top``'s BOOT column;
        ``sys_path``: entries prepended to the child's ``sys.path``
            (the repo root, a tests dir);
        ``poll_s`` / ``heartbeat_s``: child loop cadence;
        ``metrics_port``: arm the child's live ``/metrics`` exporter
            (0 = ephemeral; the bound port rides every heartbeat and
            is released on exit).
    child_faults: ``PADDLE_TPU_FAULTS``-grammar string armed INSIDE
        the child (``replica_exit_at_boot@2`` tears down incarnation 2
        at boot; engine seams like ``slow_step`` work too). The seam
        step for the boot kinds is the incarnation number, so a
        persistent-failure spec (``replica_exit_at_boot@2x99``) drives
        the crash-loop breaker deterministically.
    flight_dir: base directory for per-incarnation child artifacts
        (``<base>/<name>-inc<NNN>`` flight dumps + a stderr log per
        incarnation, so a respawn never clobbers the carcass's
        post-mortem). Default: the flight recorder's own resolution.
    env: extra environment for the child.
    python: interpreter (default: this one).
    spawn: start incarnation 1 now (False = call respawn() yourself).
    """

    _CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "proc_child.py")

    def __init__(self, name, spec, *, child_faults=None, flight_dir=None,
                 env=None, python=None, spawn=True):
        self.name = str(name)
        self.spec = dict(spec)
        self.child_faults = child_faults
        self.flight_dir = flight_dir
        self._env_extra = dict(env or {})
        self._python = python or sys.executable
        self.incarnation = 0
        self._proc = None
        self._reader = None
        self._killed = False
        self._bye = None
        self._saw_hello = False
        self._state = "down"
        self.error = None
        self._wlock = threading.Lock()     # frame writes
        self._out_lock = threading.Lock()  # outbox/unacked/mirror/health
        self._outbox = []
        self._unacked = {}                 # _rseq -> result (until ack)
        self._emit_seq = 0                 # monotonic ACROSS incarnations
        self._health = {}
        self._inflight = {}                # rid -> export_inflight mirror
        if spawn:
            self.respawn()

    # -- identity / liveness ----------------------------------------------

    @property
    def state(self):
        """booting | serving | draining | drained | dead | down."""
        return self._state

    @property
    def alive(self):
        return self._proc is not None and self._proc.poll() is None

    @property
    def pid(self):
        """The child's OS pid — what a chaos drill SIGKILLs."""
        return None if self._proc is None else self._proc.pid

    # -- lifecycle ---------------------------------------------------------

    def respawn(self):
        """Start the next incarnation (boot → warmup → hello → serving).
        The previous incarnation must be gone; its unacked results are
        RETAINED (the at-least-once response plane outlives the
        process that produced it), its in-flight mirror is dropped —
        the router already harvested it through the failover path."""
        if self.alive:
            raise RuntimeError(f"replica {self.name} is still running")
        self.incarnation += 1
        inc = self.incarnation
        self._killed = False
        self._bye = None
        self._saw_hello = False
        self.error = None
        with self._out_lock:
            self._inflight = {}
            self._health = {}
        base = self.flight_dir or _default_flight_base()
        inc_dir = os.path.join(base, f"{self.name}-inc{inc:03d}")
        os.makedirs(inc_dir, exist_ok=True)
        env = dict(os.environ)
        env.update(self._env_extra)
        # spawn-env plumbing, not a telemetry emission: the spec is a
        # finite-by-construction dict the child round-trips verbatim
        # tpulint: disable-next-line=OBS01
        env["PADDLE_TPU_PROC_SPEC"] = json.dumps(self.spec)
        env["PADDLE_TPU_FLIGHT_DIR"] = inc_dir
        env.pop("PADDLE_TPU_FAULTS", None)   # the parent's chaos wave
        #   must not leak into the child; child faults are explicit
        if self.child_faults:
            env["PADDLE_TPU_PROC_FAULTS"] = str(self.child_faults)
        stderr_log = open(os.path.join(
            base, f"{self.name}-inc{inc:03d}.stderr.log"), "wb")
        try:
            self._proc = subprocess.Popen(
                [self._python, self._CHILD, "--name", self.name,
                 "--incarnation", str(inc)],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=stderr_log, env=env)
        finally:
            stderr_log.close()
        self._state = "booting"
        self._reader = threading.Thread(
            target=self._read_loop, args=(self._proc, inc),
            daemon=True, name=f"fleet-proc-{self.name}-{inc}")
        self._reader.start()

    # rejoin() is the verb the router/recovery paths speak; for a
    # process replica a rejoin IS a respawn (fresh incarnation)
    rejoin = respawn

    def drain(self):
        """Graceful: the child stops admitting, finishes in-flight
        token-exactly, bounces queued work, emits its results and a
        ``bye``, then exits 0. Idempotent; a dead child is a no-op."""
        if self._state in ("serving", "booting", "draining"):
            self._state = "draining"
            try:
                self._send({"t": "drain"})
            except Exception:  # noqa: BLE001 — already gone: the
                pass           # reader will finalize the real state

    def kill(self, join_timeout=5.0):
        """SIGKILL the child — the real thing, not a seam. The parent
        keeps the result retention and the in-flight mirror; the
        router's failover path harvests both."""
        self._killed = True
        proc = self._proc
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
            except OSError:
                pass
        if proc is not None:
            try:
                proc.wait(timeout=join_timeout)
            except subprocess.TimeoutExpired:
                pass
        t = self._reader
        if t is not None and t is not threading.current_thread():
            t.join(timeout=join_timeout)

    close = kill

    # -- transport verbs (router-facing) -----------------------------------

    def enqueue(self, op):
        """Queue one command: same tuple shapes as InprocReplica —
        ("submit", rid, prompt, max_new, eos, priority[, extras]) or
        ("cancel", rid). A submit also seeds the parent-side in-flight
        mirror the failover path reads. Pipe failures raise
        TransientError so the ReplicaClient retry ladder (seeded
        jitter) owns the policy."""
        op = tuple(op)
        if op[0] == "submit":
            _, rid, prompt, max_new, eos, prio = op[:6]
            extras = op[6] if len(op) > 6 else {}
            frame = {"t": "submit", "rid": rid,
                     "prompt": [int(t) for t in prompt],
                     "max_new": int(max_new), "eos": eos,
                     "priority": int(prio),
                     "deadline_ms": extras.get("deadline_ms"),
                     "trace": extras.get("trace"),
                     "tenant": extras.get("tenant")}
            with self._out_lock:
                self._inflight[rid] = {
                    "rid": rid, "prompt": [int(t) for t in prompt],
                    "tokens": [], "max_new_tokens": int(max_new),
                    "eos_token_id": eos, "priority": int(prio),
                    "queued": True}
        elif op[0] == "cancel":
            frame = {"t": "cancel", "rid": op[1]}
        else:
            raise ValueError(f"unknown replica op {op[0]!r}")
        self._send(frame)

    def pop_results(self):
        """Every unacked result (at-least-once with explicit acks —
        identical retention semantics to InprocReplica; retention
        lives parent-side and survives the child, which is the point:
        a SIGKILL between finish and poll loses nothing the parent
        already read off the pipe)."""
        with self._out_lock:
            for r in self._outbox:
                self._unacked[r["_rseq"]] = r
            self._outbox = []
            return [dict(r) for r in sorted(self._unacked.values(),
                                            key=lambda r: r["_rseq"])]

    def ack(self, seqs):
        with self._out_lock:
            for s in seqs:
                self._unacked.pop(s, None)

    def scrape(self):
        """Last heartbeat snapshot, ``ts`` = parent-side arrival time
        (staleness means "how long since we heard from the process" —
        the wedge signal). Same ``scrape_timeout`` seam as the
        in-process replica."""
        if faults.pull("scrape_timeout", self.incarnation,
                       match={"replica": self.name}) is not None:
            raise faults.TransientError(
                f"DEADLINE_EXCEEDED: injected scrape_timeout "
                f"({self.name})")
        with self._out_lock:
            return dict(self._health)

    def export_inflight(self):
        """The dead/draining child's unfinished requests, from the
        parent-side mirror the ``progress`` stream maintained. Tokens
        may LAG the child's true decode position by up to one
        progress interval — the failover continuation recomputes the
        gap, greedy decoding regenerates the same tokens, so the lag
        costs wall time, never correctness."""
        with self._out_lock:
            return [dict(e) for _, e in sorted(self._inflight.items())]

    def compile_counts(self):
        """The child's per-program trace counts, as of its last
        heartbeat (the fleet zero-recompile rollup's source)."""
        with self._out_lock:
            return dict(self._health.get("compile_counts") or {})

    def unexpected_retraces(self):
        with self._out_lock:
            return int(self._health.get("unexpected_retraces") or 0)

    # -- wire --------------------------------------------------------------

    def _send(self, frame):
        proc = self._proc
        if proc is None or proc.poll() is not None \
                or proc.stdin is None or proc.stdin.closed:
            raise faults.TransientError(
                f"UNAVAILABLE: replica {self.name} process is not "
                f"accepting (state={self._state})")
        data = _frame(frame)
        try:
            with self._wlock:
                proc.stdin.write(data)
                proc.stdin.flush()
        except (BrokenPipeError, OSError, ValueError) as e:
            raise faults.TransientError(
                f"UNAVAILABLE: replica {self.name} pipe write failed "
                f"({type(e).__name__}: {e})") from e

    def _read_loop(self, proc, inc):
        """Reader for one incarnation's stdout: decode frames, keep
        the health snapshot / result plane / in-flight mirror current,
        finalize the replica state at EOF. A torn frame (SIGKILL
        mid-write) is dropped by the FrameReader; a clean exit is
        whatever the ``bye`` said."""
        fr = FrameReader()
        fd = proc.stdout.fileno()
        while True:
            try:
                data = os.read(fd, 1 << 16)
            except OSError:
                data = b""
            if not data:
                break
            for rec in fr.feed(data):
                self._dispatch(rec, inc)
        rc = proc.wait()
        if self.incarnation != inc:
            return   # a later incarnation owns the state now
        bye = self._bye
        if bye is not None and bye.get("state") == "drained":
            self._state = "drained"
        else:
            self._state = "dead"
            if self._killed:
                self.error = self.error or "killed"
            elif not self._saw_hello:
                self.error = f"exit at boot (rc={rc})"
            else:
                self.error = f"exited rc={rc}"
        with self._out_lock:
            if self._health:
                self._health = dict(self._health, state=self._state,
                                    error=self.error)

    def _dispatch(self, rec, inc):
        t = rec.get("t")
        if t != "result" and self.incarnation != inc:
            # a previous incarnation's reader draining its buffered
            # frames after a respawn: its RESULTS are still real (the
            # retention plane outlives the process; the router's
            # incarnation guard owns staleness), but its health/state
            # — and its progress frames, whose tokens are relative to
            # the OLD leg's prefix — must not clobber the new
            # incarnation's mirror
            return
        if t == "hb":
            snap = {k: v for k, v in rec.items() if k != "t"}
            snap["publish_ts"] = snap.get("ts")
            snap["ts"] = time.monotonic()   # arrival = freshness
            snap["incarnation"] = inc
            with self._out_lock:
                self._health = snap
            if self._state in ("booting", "serving", "draining") \
                    and snap.get("state") in ("serving", "draining"):
                # a drain() intent set parent-side sticks until the
                # child confirms; otherwise mirror the child
                if not (self._state == "draining"
                        and snap["state"] == "serving"):
                    self._state = snap["state"]
        elif t == "hello":
            self._saw_hello = True
        elif t == "result":
            res = rec.get("res") or {}
            with self._out_lock:
                self._emit_seq += 1
                self._outbox.append(dict(
                    res, replica=self.name, incarnation=inc,
                    _rseq=self._emit_seq))
                if self.incarnation == inc:
                    # a stale incarnation's result must not evict the
                    # NEW incarnation's mirror entry for a re-placed rid
                    self._inflight.pop(res.get("id"), None)
        elif t == "progress":
            with self._out_lock:
                ent = self._inflight.get(rec.get("rid"))
                if ent is not None:
                    ent["tokens"] = [int(x)
                                     for x in rec.get("tokens") or []]
                    ent["queued"] = False
        elif t == "bye":
            self._bye = rec

    def __repr__(self):
        return (f"ProcReplica({self.name!r} inc={self.incarnation} "
                f"pid={self.pid} state={self._state})")
