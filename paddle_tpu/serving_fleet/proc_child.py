"""Subprocess serving replica — the child half of ``ProcReplica``.

Run as a PLAIN SCRIPT (never imported by the parent): it has no
package context until it bootstraps ``sys.path`` from its spec, which
keeps the pre-boot fault seams cheap — an injected exit-at-boot costs
milliseconds, not a paddle_tpu import.

Boot sequence:

1. read the spec (``PADDLE_TPU_PROC_SPEC``, JSON) + name/incarnation
   from argv; point the flight recorder at the per-incarnation dir the
   parent chose (a respawn must never clobber the carcass's
   post-mortem);
2. consult the boot fault seams with the INCARNATION as the seam step
   (``replica_exit_at_boot`` → exit now, nonzero;
   ``replica_slow_boot`` → sleep ``seconds`` before the heavy import,
   so a supervisor's boot gate sees a genuinely slow boot). The faults
   module is file-loaded (stdlib-only by contract) so this happens
   before any heavy import;
3. claim the wire: dup stdout onto a private fd and redirect fd 1 to
   stderr, so stray library prints can never interleave with frames;
4. heavy boot: import the builder from the spec, build the engine,
   ``warmup()`` the spec'd prefill buckets + decode program — the
   warm-boot contract: every compile this incarnation will ever need
   happens HERE, before the hello, so traffic after the boot gate
   runs under frozen compile counts;
5. serve: pump submit/cancel/drain ops from stdin (idempotent by
   fleet rid, same ledger discipline as ``InprocReplica``), step the
   engine, stream ``result`` + ``progress`` + throttled ``hb`` frames.

Shutdown hygiene (the round-14 satellite):

- SIGTERM installs a drain flag (handler set before the heavy boot):
  in-flight requests finish token-exactly, queued work bounces, every
  result is emitted, then a ``bye`` seals the stream and the process
  exits 0 — the subprocess analogue of the round-8
  checkpoint-and-exit contract;
- stdin EOF (the parent died) drains the same way after a short
  seeded-backoff retry (a transient empty read must not kill a
  healthy replica) — no orphan processes;
- the ``/metrics`` exporter port (when armed via ``metrics_port``) is
  released in ``finally``, so the next incarnation can bind it.
"""
from __future__ import annotations

import argparse
import importlib
import importlib.util
import json
import os
import signal
import sys
import threading
import time


def _load_faults_standalone():
    """File-load resilience/faults.py (stdlib-only by contract) so the
    boot seams fire before any heavy import."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir, "resilience", "faults.py")
    spec = importlib.util.spec_from_file_location("_proc_child_faults",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _resolve_builder(spec):
    b = spec.get("builder")
    if isinstance(b, dict):
        mspec = importlib.util.spec_from_file_location(
            "_proc_child_builder", b["path"])
        mod = importlib.util.module_from_spec(mspec)
        mspec.loader.exec_module(mod)
        return getattr(mod, b["fn"])
    modname, fn = str(b).split(":", 1)
    return getattr(importlib.import_module(modname), fn)


class _Child:
    def __init__(self, name, incarnation, spec, wire):
        self.name = name
        self.incarnation = incarnation
        self.spec = spec
        self.wire = wire
        self.poll_s = float(spec.get("poll_s", 0.002))
        self.heartbeat_s = float(spec.get("heartbeat_s", 0.02))
        self.drain_flag = threading.Event()
        self.engine = None
        self.exporter = None
        self._frame = None          # journal._frame, bound post-import
        self._ops = []
        self._ops_lock = threading.Lock()
        self._stdin_eof = False
        self._accepted = {}         # fleet rid -> engine rid
        self._rid_map = {}          # engine rid -> fleet rid
        self._precancel = set()
        self._progress_sent = {}    # fleet rid -> tokens emitted
        self._last_hb = 0.0
        self._rounds = 0
        self.state = "serving"
        self.warmed = False

    # -- wire --------------------------------------------------------------

    def emit(self, rec):
        self.wire.write(self._frame(rec))

    def heartbeat(self, force=False):
        now = time.monotonic()
        if not force and now - self._last_hb < self.heartbeat_s:
            return
        self._last_hb = now
        h = self.engine.health()
        qw = self.engine.registry.get("serve_queue_wait_seconds")
        p99 = qw.quantile(0.99) if qw is not None and qw.count else 0.0
        self.emit({
            "t": "hb", "replica": self.name, "state": self.state,
            "engine_state": h.get("state"), "ts": now,
            "round": self._rounds, "pid": os.getpid(),
            "warmed": self.warmed,
            "queued": h["queued"], "running": h["running"],
            "free_pages": h["free_pages"],
            "total_pages": h["total_pages"],
            "page_occupancy": h["page_occupancy"],
            "page_size": self.engine.page_size,
            "queue_wait_p99_s": round(float(p99 or 0.0), 6),
            "decode_tokens": h["decode_tokens"],
            "tenants_tracked": h.get("tenants_tracked", 0),
            "sampling": h.get("sampling"),
            "prefix_cache": h.get("prefix_cache"),
            "spec": h.get("spec"),
            "mem": h.get("mem"),
            "boot": h.get("boot"),
            "compile_counts": h["compile_counts"],
            "unexpected_retraces":
                self.engine.tracer.unexpected_retraces(),
            "metrics_port": None if self.exporter is None
            else self.exporter.port})

    # -- stdin op pump -----------------------------------------------------

    def _stdin_loop(self):
        """Read op frames off fd 0. A transient empty read retries on
        a seeded backoff (jitter_seed = incarnation, so each boot's
        schedule replays bit-identically); persistent EOF means the
        parent is gone — drain and exit rather than orphan."""
        from paddle_tpu.resilience.retry import backoff_schedule
        from paddle_tpu.serving_fleet.proc import FrameReader
        delays = backoff_schedule(3, base_delay=0.01, max_delay=0.1,
                                  jitter=0.5,
                                  jitter_seed=self.incarnation)
        fr = FrameReader()
        eofs = 0
        while True:
            try:
                data = os.read(0, 1 << 16)
            except OSError:
                data = b""
            if not data:
                if eofs < len(delays):
                    time.sleep(delays[eofs])
                    eofs += 1
                    continue
                self._stdin_eof = True
                self.drain_flag.set()
                return
            eofs = 0
            recs = fr.feed(data)
            if recs:
                with self._ops_lock:
                    self._ops.extend(recs)

    def _pump_ops(self):
        with self._ops_lock:
            ops, self._ops = self._ops, []
        for op in ops:
            t = op.get("t")
            if t == "submit":
                self._op_submit(op)
            elif t == "cancel":
                erid = self._accepted.get(op.get("rid"))
                if erid is not None:
                    self.engine.cancel(erid)
                else:
                    self._precancel.add(op.get("rid"))
            elif t == "drain":
                self.drain_flag.set()

    def _op_submit(self, op):
        frid = op["rid"]
        if frid in self._accepted:
            return     # idempotent: duplicate delivery dropped
        if frid in self._precancel:
            self._precancel.discard(frid)
            self.emit({"t": "result", "res": {
                "id": frid, "tokens": [], "status": "cancelled"}})
            return
        if self.state != "serving" or self.engine.state != "serving":
            self.emit({"t": "result", "res": {
                "id": frid, "tokens": [], "status": "bounced"}})
            return
        erid = self.engine.submit(
            op["prompt"], op["max_new"], op.get("eos"),
            priority=int(op.get("priority") or 0),
            deadline_ms=op.get("deadline_ms"),
            trace=op.get("trace"),
            tenant=op.get("tenant"))
        self._accepted[frid] = erid
        self._rid_map[erid] = frid

    # -- engine results / progress ----------------------------------------

    def _emit_engine(self, res):
        frid = self._rid_map.get(res["id"])
        if frid is None:
            return     # engine-local (warmup) — not fleet-owned
        if res.get("status") in ("ok", "expired", "cancelled"):
            # terminal: retire from the idempotency ledger (same
            # contract as InprocReplica._emit_engine — a later
            # re-submit of the rid is a fresh run, and the router's
            # resolved-rid dedup owns the at-least-once edge)
            self._accepted.pop(frid, None)
        self._progress_sent.pop(frid, None)
        out = {k: v for k, v in res.items() if k != "prompt"}
        self.emit({"t": "result", "res": dict(out, id=frid)})

    def _emit_progress(self):
        """Stream partial tokens for every live slot whose count grew:
        the channel the parent's export_inflight mirror — and so the
        router's failover harvest — is built from."""
        for ent in self.engine.export_inflight():
            frid = self._rid_map.get(ent["rid"])
            if frid is None or ent["queued"]:
                continue
            n = len(ent["tokens"])
            if n != self._progress_sent.get(frid):
                self._progress_sent[frid] = n
                self.emit({"t": "progress", "rid": frid,
                           "tokens": [int(x) for x in ent["tokens"]]})

    # -- main loop ---------------------------------------------------------

    def run(self):
        threading.Thread(target=self._stdin_loop, daemon=True,
                         name="proc-child-stdin").start()
        self.heartbeat(force=True)
        while True:
            self._rounds += 1
            self._pump_ops()
            if self.drain_flag.is_set():
                if self.engine.state == "serving":
                    self.engine.drain()
                self.state = "draining"
            if not self.engine.idle:
                for res in self.engine.step():
                    self._emit_engine(res)
                self._emit_progress()
            elif self.state == "draining":
                break
            else:
                time.sleep(self.poll_s)
            self.heartbeat()
        self.state = "drained"
        self.heartbeat(force=True)
        self.emit({"t": "bye", "state": "drained",
                   "reason": "eof" if self._stdin_eof else "drain"})


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--name", required=True)
    ap.add_argument("--incarnation", type=int, required=True)
    args = ap.parse_args(argv)
    spec = json.loads(os.environ.get("PADDLE_TPU_PROC_SPEC") or "{}")

    # boot fault seams FIRST (stdlib-only file-load; step=incarnation)
    pf = os.environ.get("PADDLE_TPU_PROC_FAULTS")
    if pf:
        os.environ["PADDLE_TPU_FAULTS"] = pf
    faults = _load_faults_standalone()
    faults.load_env()
    p = faults.pull("replica_exit_at_boot", args.incarnation)
    if p is not None:
        sys.exit(int(p.get("exit_code", 7)))
    faults.maybe_sleep("replica_slow_boot", args.incarnation)

    # drain flag armed before the heavy boot: a SIGTERM mid-compile
    # still drains at the first loop round
    drain_flag = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: drain_flag.set())

    # claim the wire: frames go to the dup'd fd; anything the heavy
    # imports print to "stdout" lands on stderr instead
    wire_fd = os.dup(1)
    os.dup2(2, 1)
    sys.stdout = sys.stderr
    wire = os.fdopen(wire_fd, "wb", buffering=0)

    for entry in reversed(spec.get("sys_path") or []):
        if entry not in sys.path:
            sys.path.insert(0, entry)
    if spec.get("force_cpu"):
        # the conftest guard, replicated for the child process: the
        # axon register hook sets jax_platforms via config (overrides
        # env) and its lazy client connect can stall a CPU-only child
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        import jax._src.xla_bridge as xb
        jax.config.update("jax_platforms", "cpu")
        for reg in ("_backend_factories", "backend_factories"):
            d = getattr(xb, reg, None)
            if isinstance(d, dict):
                d.pop("axon", None)

    t_boot = time.monotonic()
    builder = _resolve_builder(spec)
    engine = builder(**(spec.get("kwargs") or {}))
    from paddle_tpu.serving_fleet.journal import _frame

    child = _Child(args.name, args.incarnation, spec, wire)
    child._frame = _frame
    child.engine = engine
    child.drain_flag = drain_flag
    exporter = None
    try:
        if spec.get("metrics_port") is not None:
            exporter = engine.serve_metrics(
                port=int(spec["metrics_port"]))
            child.exporter = exporter
        # warm boot: the spec'd prefill buckets plus (always, unless
        # warmup=False) the decode program — heartbeats report the
        # ENGINE's warmed flag, never an unconditional claim, so the
        # supervisor's boot gate can't admit a cold replica. With an
        # artifact store configured (spec aot_dir / PADDLE_TPU_AOT_DIR)
        # the boot ladder prefers the AOT artifact — incarnation 1
        # traces and exports, every respawn after it boots from
        # serialized StableHLO in seconds; a torn/stale/corrupt
        # artifact falls back loudly (serve_aot_fallback_total) to the
        # traced path, so the gate can never admit a wrong program
        warm = spec.get("warmup")
        if warm is not False:
            from paddle_tpu.jit.serving_artifact import warm_boot
            warm_boot(engine, buckets=warm or (),
                      artifact_dir=spec.get("aot_dir"))
        child.warmed = bool(engine.warmed)
        child.emit({"t": "hello", "pid": os.getpid(),
                    "incarnation": args.incarnation,
                    "warmed": child.warmed,
                    "boot_s": round(time.monotonic() - t_boot, 6),
                    "boot": dict(engine.boot_info),
                    "compile_counts": engine.compile_counts()})
        child.run()
    finally:
        # release the exporter's port NOW — the next incarnation may
        # want to bind the same one
        if exporter is not None:
            exporter.close()
        try:
            wire.flush()
        except OSError:
            pass


if __name__ == "__main__":
    main()
