"""FleetRouter — health-routed load balancing over N serving replicas.

The fleet layer the ROADMAP's "millions of users" north star needs: a
single admission point over many ServingEngine replicas that keeps
every client request alive through replica crashes, wedges, drains
and saturation — with the same zero-recompile discipline the engines
themselves keep (every mechanism below is host-side bookkeeping; no
replica ever compiles anything because of the router).

Mechanisms (docs/robustness.md "Fleet serving" has the contracts):

- **Placement by scrape.** Requests enter a global queue and are
  placed by scoring each replica's last published health/metrics
  snapshot (free KV pages, queued/running depth, queue-wait p99,
  lifecycle state) — the same facts the round-10 ``/metrics`` +
  ``/healthz`` endpoints expose, so a real multi-process deployment
  scrapes HTTP instead of a lock. Stale scrapes degrade gracefully
  (route on the previous snapshot; count ``fleet_scrape_errors``).
- **Failover with prefix dedup.** A dead (``replica_crash``) or
  silent (``replica_wedge``, heartbeat older than
  ``wedge_timeout_s``) replica's unfinished requests are recovered
  from its carcass (``export_inflight``) and continuation-resubmitted
  elsewhere: the new prompt is ``original ‖ tokens_already_decoded``
  and only the REMAINING budget is requested, so the client's final
  stream is the completed prefix + the continuation — token-exact
  under greedy decoding, never a duplicated token.
- **Hedging.** With ``hedge_after_ms`` set, a request stuck past the
  threshold on its primary gets a duplicate on the next-best replica;
  the first finisher wins and the loser is cancelled (first-winner
  dedup — the client sees exactly one result).
- **Graceful drain / rejoin.** ``drain(name)`` flows through the
  replica into ``ServingEngine.drain()`` (the resilience/preemption
  seam: a process-level SIGTERM drains every replica the same way):
  in-flight requests finish token-exactly, queued ones bounce back
  and re-place on healthy replicas. ``rejoin(name)`` restarts the
  worker on the SAME engine — compiled programs carry over, so a full
  drain/rejoin cycle costs zero recompiles.
- **Load shedding by priority.** When every serving replica is at its
  outstanding-work limit and the global queue exceeds ``max_queue``,
  the lowest-priority (newest-first within a priority) queued
  requests resolve with ``status="shed"`` — predictable degradation
  instead of unbounded queueing.

The router publishes its own MetricsRegistry (catalogue in
docs/observability.md) and serves it live via ``serve_metrics()`` —
the router is itself a scrape target. Control flow is single-threaded
by design: one thread drives ``step()``/``run_to_completion()``;
replica workers run on their own daemon threads behind the transport
seam.
"""
from __future__ import annotations

import collections
import contextlib
import os
import time

from ..observability import dtrace
from ..observability.history import HistoryStore
from ..observability.metrics import MetricsRegistry
from ..observability.sentinel import AnomalySentinel
from ..observability.slo import SLOTracker
from ..observability.tenancy import TenantAccountant
from ..observability.trafficrec import TrafficRecorder
from ..resilience import faults, preemption
from .client import ReplicaClient
from .journal import Journal, JournalCrash, JournalError, reconcile, \
    replay

__all__ = ["FleetRouter", "RouterCrash"]


def labeled_counter(registry, cache, name, help, **labels):
    """Lazy per-label-set counter creation (one shared implementation
    for the router and the supervisor — the PR-6 dedup, kept)."""
    key = tuple(sorted(labels.items()))
    c = cache.get(key)
    if c is None:
        c = registry.counter(name, help=help, labels=labels)
        cache[key] = c
    return c


class RouterCrash(RuntimeError):
    """Injected stand-in for the router process dying mid-control-
    round (``router_crash`` fault kind). The chaos drill catches it,
    abandons the router WITHOUT close() (the replicas keep running,
    exactly like real replica processes outliving their control
    plane), and brings up a successor via ``FleetRouter.recover``."""


class _Pending:
    """Router-side state of one fleet request."""

    __slots__ = ("rid", "prompt", "max_new", "eos", "priority",
                 "submitted_at", "placed_at", "replica", "hedge",
                 "delivered", "failovers", "hedged", "done",
                 "deadline", "trace", "queue_since_pc", "leg_ctxs",
                 "leg_base", "leg_inc", "tenant", "captured",
                 "prefix_fps")

    def __init__(self, rid, prompt, max_new, eos, priority,
                 deadline=None, tenant=None):
        self.rid = rid
        self.prompt = [int(t) for t in prompt]
        self.max_new = int(max_new)
        self.eos = eos
        self.priority = int(priority)
        self.tenant = None if tenant is None else str(tenant)
        self.submitted_at = time.monotonic()
        self.placed_at = None
        self.replica = None     # primary assignment (replica name)
        self.hedge = None       # hedge assignment (replica name)
        self.delivered = []     # tokens recovered from a lost replica
        self.failovers = 0
        self.hedged = False
        self.done = False
        self.deadline = deadline   # abs monotonic (None = none)
        self.trace = None          # dtrace root context
        self.queue_since_pc = dtrace.now()  # current queue leg start
        self.leg_ctxs = {}         # replica name -> open leg context
        self.leg_base = {}         # replica name -> len(delivered) the
        #                            leg was placed with: its token
        #                            stream is relative to THAT prefix,
        #                            so every fold/stitch of leg tokens
        #                            must anchor there, not at whatever
        #                            delivered has since become
        self.leg_inc = {}          # replica name -> replica INCARNATION
        #                            the leg was placed with: a result
        #                            stamped with any other incarnation
        #                            of that name is a stale leg (the
        #                            replica respawned/rejoined since)
        #                            and is dropped in _handle
        self.captured = None       # traffic-archive locator
        #                            ({"segment","offset"}) when this
        #                            request was captured at admission
        self.prefix_fps = None     # page_size -> prompt prefix
        #                            fingerprints (affinity memo)


class FleetRouter:
    """Fault-tolerant request router over serving replicas.

    replicas: iterable of InprocReplica (names must be unique).
    registry: MetricsRegistry for the fleet_* series (default: a
        private one, mirroring ServingEngine's registry semantics).
    max_queue: global placement-queue bound; beyond it the lowest-
        priority queued requests are shed.
    replica_queue_limit: max outstanding (router-placed, unfinished)
        requests per replica — the saturation definition.
    hedge_after_ms: duplicate a request stuck this long on its
        primary onto a second replica (None = hedging off).
    wedge_timeout_s: a live replica whose heartbeat is older than
        this is declared wedged, killed, and failed over. The worker
        can only heartbeat BETWEEN engine rounds, so this must exceed
        the worst single dispatch/compile the replica can legally pay
        (an unwarmed prefill bucket on real hardware is seconds) —
        too tight a timeout turns a slow compile into a fleet-wide
        kill cascade. Default 10s; chaos tests pin it low only
        because their buckets are pre-warmed.
    transport_retries / retry_jitter: ReplicaClient backoff knobs;
        each client gets a distinct jitter seed so fleet-wide retries
        de-synchronize (resilience.retry.backoff_schedule).
    trace_store: observability.dtrace.TraceStore the request span
        trees land in. Default the process-global store — the SAME
        one the engines record their queue/prefill/decode legs into,
        which is what makes the trees causally complete; pass a
        private store only when router-side spans alone are enough.
    attribution_tolerance: allowed unattributed fraction of a
        request's end-to-end wall time before trace_report flags it
        (docs/observability.md "Distributed tracing & SLOs").
    slos: SLObjective iterable (None = the default TTFT-p99 /
        e2e-p99 / availability trio; False disables SLO accounting).
    slo_windows: burn-rate window pairs for the SLOTracker.
    shed_storm_threshold / shed_storm_window_s: sheds inside the
        window before the flight recorder dumps a shed-storm record
        (re-arms once the window drains).
    journal_dir: directory for the write-ahead request journal
        (serving_fleet.journal; None = no durability). With a journal,
        every lifecycle transition the router owns is logged before it
        commits, submit() REJECTS (raises JournalError) when the
        admission record cannot be made durable, a preemption notice
        seals the journal before the drain, and a successor router
        rebuilds the whole in-flight picture via
        ``FleetRouter.recover(journal_dir, replicas)``.
    journal_fsync_every / journal_segment_max_bytes: Journal knobs
        (fsync cadence; rotation/compaction threshold).
    tenants: per-tenant usage accounting (observability.tenancy) —
        None/True = a bounded space-saving TenantAccountant of
        ``tenant_capacity`` heavy hitters (default ON: cardinality is
        bounded, untagged traffic lands under "anon" so sketch totals
        equal fleet totals EXACTLY); False disables; or pass an
        accountant. Served at ``/tenants`` and folded into the
        priority-shed order (heaviest tenants shed first within a
        priority band).
    history / history_interval_s: telemetry history plane
        (observability.history) — True = a HistoryStore scraping THIS
        registry every ``history_interval_s`` seconds from the
        control loop (no extra thread); or pass a store; None = off.
        Served at ``/history``.
    sentinel / sentinel_kw: online anomaly detection
        (observability.sentinel) — True = an AnomalySentinel over the
        history plane (created if absent) watching TTFT p99, decode
        tok/s, placement wait, journal errors and any recompile
        delta; fires ``fleet_anomaly`` flight dumps + counters and
        surfaces in health()["anomaly"] exactly like SLO burn alerts.
        sentinel_kw tunes bands (z/warmup/min_consecutive/signals).
    capture / capture_kw: traffic capture
        (observability.trafficrec) — a directory path creates a
        TrafficRecorder there (capture_kw forwarded: sample,
        segment_max_bytes, max_segments); or pass a recorder; None
        disables. Every ADMITTED request writes an ``arrival`` record
        at submit and a ``resolve`` record (output tokens + compact
        per-hop attribution) at resolve; captured requests force-keep
        their span tree whatever PADDLE_TPU_TRACE_SAMPLE says, so an
        archive entry always carries its attribution
        (``fleet_capture_trace_missing_total`` counts divergences).
        ``tools/fleet_replay.py`` re-drives a fleet from the archive.
    placement_weights: score weights for ``_pick_replica`` — dict
        over {"free_pages", "queued", "running", "queue_wait_p99_s",
        "outstanding", "prefix_affinity"} merged over the defaults
        (1, 8, 2, 50, 4, 0). A replay what-if knob as much as an
        operator one. ``prefix_affinity`` scores each candidate by
        the number of leading prompt pages already resident in its
        prefix cache (fingerprints advertised on heartbeats) — the
        default 0 preserves pre-affinity placement exactly; replay
        scores alternatives via ``--knob placement.prefix_affinity``.
    overload_target_ms / overload_interval_s: the adaptive overload
        control layer (CoDel-style queue-delay admission,
        docs/robustness.md "Elastic autoscaling & overload control").
        When the head-of-line placement sojourn stays above the
        target for a full interval WITH nothing placeable, the router
        enters ``degraded``: queued requests whose sojourn already
        exceeds the target shed fail-fast (tenant-fair order — the
        static ``max_queue`` stays only as a hard backstop), and the
        brownout ladder below starts climbing. None disables (static
        max_queue only).
    brownout_max_new / brownout_levels / brownout_step_s: tenant-fair
        brownout — while degraded the level climbs one rung every
        ``brownout_step_s`` up to ``brownout_levels`` and decays the
        same way after recovery; at level L the L HEAVIEST tenants
        (space-saving sketch weight) have their decode budgets
        clamped to ``brownout_max_new`` at placement. Degradation
        lands on whoever is causing the load first, is journaled
        (``brownout`` records) and honestly visible in
        ``health()["overload"]``.
    """

    def __init__(self, replicas, *, registry=None, max_queue=64,
                 replica_queue_limit=4, hedge_after_ms=None,
                 wedge_timeout_s=10.0, transport_retries=3,
                 retry_jitter=0.5, trace_store=None,
                 attribution_tolerance=0.05, slos=None,
                 slo_windows=None, shed_storm_threshold=16,
                 shed_storm_window_s=5.0, journal_dir=None,
                 journal_fsync_every=1,
                 journal_segment_max_bytes=1 << 20,
                 tenants=None, tenant_capacity=128,
                 history=None, history_interval_s=0.25,
                 sentinel=None, sentinel_kw=None,
                 capture=None, capture_kw=None,
                 placement_weights=None,
                 overload_target_ms=2000.0, overload_interval_s=1.0,
                 brownout_max_new=4, brownout_levels=3,
                 brownout_step_s=2.0,
                 profile=None, profile_hz=None):
        self.replicas = {}
        self._clients = {}
        self._transport_retries = int(transport_retries)
        self._retry_jitter = float(retry_jitter)
        # monotonic, never reused: a client seed freed by
        # remove_replica must not be handed to a later adoption, or
        # two replicas' retry-jitter ladders re-synchronize
        self._next_client_seed = 0
        for rep in replicas:
            if rep.name in self.replicas:
                raise ValueError(f"duplicate replica name {rep.name!r}")
            self.replicas[rep.name] = rep
            self._clients[rep.name] = self._new_client(rep)
        if not self.replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.max_queue = int(max_queue)
        self.replica_queue_limit = int(replica_queue_limit)
        self.hedge_after_ms = hedge_after_ms
        self.wedge_timeout_s = float(wedge_timeout_s)
        self.placement_weights = {
            "free_pages": 1.0, "queued": 8.0, "running": 2.0,
            "queue_wait_p99_s": 50.0, "outstanding": 4.0,
            "prefix_affinity": 0.0, "mem_headroom": 0.0}
        if placement_weights:
            unknown = set(placement_weights) - set(
                self.placement_weights)
            if unknown:
                raise ValueError(
                    f"unknown placement weight(s) {sorted(unknown)}; "
                    f"known: {sorted(self.placement_weights)}")
            self.placement_weights.update(
                {k: float(v) for k, v in placement_weights.items()})

        self._pending = {}          # rid -> _Pending (retired when the
        #                             result is popped via results())
        self._queue = []            # rids awaiting placement
        self._done = {}             # rid -> result dict (until popped)
        self._cancel_requested = set()
        self._lost = set()          # failed-over, awaiting rejoin
        self._last_scrape = {}      # name -> last good snapshot
        self._next_rid = 0
        self._exporter = None
        self._closed = False

        # -- distributed tracing: one span tree per request, engines
        # append their legs through the propagated context (dtrace)
        self._tstore = trace_store if trace_store is not None \
            else dtrace.get_store()
        self.attribution_tolerance = float(attribution_tolerance)
        self._trace_ids = collections.deque(maxlen=512)
        self._clock_offsets = {}    # name -> estimated skew upper
        #                             bound (heartbeat one-way delay)
        # -- flight-recorder shed-storm window
        self._shed_storm_threshold = int(shed_storm_threshold)
        self._shed_storm_window_s = float(shed_storm_window_s)
        self._shed_times = collections.deque(maxlen=4096)
        self._shed_storm_armed = True
        # -- adaptive overload control (CoDel-style sojourn admission
        # + tenant-fair brownout). All host-side bookkeeping driven
        # from the control loop; the FleetAutoscaler reads `degraded`
        # as one of its scale-out signals
        self._overload_target_s = None if overload_target_ms is None \
            else float(overload_target_ms) / 1e3
        self._overload_interval_s = float(overload_interval_s)
        self._overload_since = None   # head sojourn first over target
        self._degraded = False
        self._degraded_at = None
        self._brownout_max_new = int(brownout_max_new)
        self._brownout_levels = int(brownout_levels)
        self._brownout_step_s = float(brownout_step_s)
        self._brownout_level = 0
        self._brownout_changed = 0.0
        self._brownout_set = set()   # tenants clamped at this level
        # the FleetAutoscaler attaches itself here (serving_fleet/
        # autoscaler.py); health() folds its cached rollup in
        self.autoscaler = None
        # bounded log of scale/brownout decision records: carried
        # through snapshot compaction so "why is the fleet this
        # size" survives any number of crash/recover cycles, not
        # just until the next rotate(). recover() seeds it (and
        # recovered_autoscale) from the dead incarnation's journal
        self._scale_log = collections.deque(maxlen=64)
        self.recovered_autoscale = []

        self.registry = registry if registry is not None \
            else MetricsRegistry()
        reg = self.registry
        # -- write-ahead journal (router durability): lifecycle
        # records append BEFORE their in-memory transition commits;
        # transient append failures park in a backlog retried every
        # step (results whose `resolved` record is still un-durable
        # are NOT acked at their replica, so a crash re-surfaces them)
        self._journal = None
        self._jbacklog = []        # (kind, fields) appends to retry
        self._junacked_rids = set()
        self._step_n = 0
        self._m_recovered = None
        if journal_dir is not None:
            self._journal = Journal(
                journal_dir, fsync_every=journal_fsync_every,
                segment_max_bytes=journal_segment_max_bytes,
                registry=reg)
            self._m_recovered = reg.counter(
                "fleet_journal_recovered_requests_total",
                help="unresolved requests reinstated by router "
                     "recovery")
        # SLO burn-rate accounting (observability.slo): evaluated once
        # per step(), gauges land in the fleet registry, alert rollup
        # cached for health() so placement/operators see burn state
        self.slo = None if slos is False else SLOTracker(
            objectives=slos, windows=slo_windows, registry=reg)
        self._slo_state = {}
        self._slo_eval_at = 0.0
        # -- tenancy: bounded heavy-hitter usage attribution. Untagged
        # requests account under "anon", so the sketch's exact-totals
        # invariant (sum over tenants == fleet counters) holds
        # unconditionally, not only on fully-tagged traffic
        if tenants is False:
            self.tenants = None
        elif tenants is None or tenants is True:
            self.tenants = TenantAccountant(capacity=tenant_capacity,
                                            registry=reg)
        else:
            self.tenants = tenants
        # -- telemetry history plane + anomaly sentinel: both are
        # driven from the control loop's existing heartbeat (no new
        # threads; HistoryStore.start() exists for loop-less hosts)
        if history is True:
            history = HistoryStore(reg, interval_s=history_interval_s)
        self.history = history if history else None
        self._anomaly_state = {}
        if sentinel is True:
            if self.history is None:
                self.history = HistoryStore(
                    reg, interval_s=history_interval_s)
            sentinel = AnomalySentinel(
                self.history, registry=reg,
                compile_fn=self.compile_report,
                **(sentinel_kw or {}))
        self.sentinel = sentinel if sentinel else None
        # -- traffic capture plane: arrival/resolve records per
        # admitted request into a bounded rotating archive — the
        # replay harness's (tools/fleet_replay.py) input. Best-effort
        # by contract: a capture failure costs a record, never the
        # serving path
        if capture is None or capture is False:
            self.recorder = None
        elif isinstance(capture, (str, os.PathLike)):
            self.recorder = TrafficRecorder(
                capture, registry=reg, **(capture_kw or {}))
        else:
            self.recorder = capture
        # recent-resolved index (the /requests endpoint): one row per
        # resolved request with its archive locator, bounded like the
        # trace-id ring so a scraper can find a request without
        # scanning archives
        self._requests_index = collections.deque(maxlen=512)
        self._m_req = {}
        self._m_routed = {}
        self._m_failover = {}
        self._m_requeued = reg.counter(
            "fleet_requeued_total",
            help="requests re-placed after a drain bounce")
        self._m_hedges = reg.counter(
            "fleet_hedges_total",
            help="duplicate submissions issued by tail-latency hedging")
        self._m_hedge_wins = {}
        self._m_shed = reg.counter(
            "fleet_shed_total",
            help="requests rejected by priority load shedding")
        self._m_scrape_errors = reg.counter(
            "fleet_scrape_errors_total",
            help="replica health scrapes that failed (stale routing)")
        self._m_place_wait = reg.histogram(
            "fleet_placement_wait_seconds",
            help="submit -> placement-decision wait (the router-level "
                 "queueing leg)")
        # fleet-level token/latency series: the history plane's inputs
        # (the sentinel's TTFT-p99 / decode-tok/s / queue-wait signals
        # all read these back through quantile/rate-over-time)
        self._m_tokens_in = reg.counter(
            "fleet_tokens_in_total",
            help="prompt tokens of resolved fleet requests")
        self._m_tokens_out = reg.counter(
            "fleet_tokens_out_total",
            help="generated tokens delivered in resolved results")
        self._m_ttft_h = reg.histogram(
            "fleet_ttft_seconds",
            help="submit -> first generated token, fleet level "
                 "(trace-derived; absent for sampled-out traces)")
        self._m_e2e_h = reg.histogram(
            "fleet_e2e_seconds",
            help="submit -> resolve wall time of ok requests")
        self._g_queue = reg.gauge(
            "fleet_queue_depth", help="requests awaiting placement")
        self._g_pending = reg.gauge(
            "fleet_pending", help="accepted, unresolved requests")
        self._g_serving = reg.gauge(
            "fleet_replicas_serving",
            help="replicas currently placeable")
        self._g_degraded = reg.gauge(
            "fleet_degraded",
            help="1 while the overload controller sees a standing "
                 "placement queue (sojourn over target for a full "
                 "interval with nothing placeable)")
        self._g_blevel = reg.gauge(
            "fleet_brownout_level",
            help="current brownout rung (0 = none; level L clamps "
                 "the L heaviest tenants' decode budgets)")
        self._m_bclamp = {}
        self._m_osheds = reg.counter(
            "fleet_overload_sheds_total",
            help="queued requests shed by the sojourn-based overload "
                 "controller (also counted in fleet_shed_total)")
        # -- prefix-cache plane: fleet rollups folded from replica
        # heartbeats (engine-monotonic stats, delta-folded per scrape
        # so a respawned replica's reset never decrements), plus the
        # per-replica fingerprint inventories the affinity term in
        # _pick_replica scores against. Registered at 0 up front —
        # a cold fleet exports the whole catalogue.
        self._m_prefix = {
            "hits": reg.counter(
                "fleet_prefix_hits_total",
                help="prefix-cache hit admissions across the fleet "
                     "(folded from replica heartbeats)"),
            "misses": reg.counter(
                "fleet_prefix_misses_total",
                help="admissions with a shareable prefix that missed "
                     "every replica prefix cache they landed on"),
            "adopted_pages": reg.counter(
                "fleet_prefix_shared_pages_total",
                help="prompt KV pages adopted into replica prefix "
                     "caches (shareable immutable pages published)"),
            "cow_copies": reg.counter(
                "fleet_prefix_cow_copies_total",
                help="private tail pages materialized at hit "
                     "admissions (the copy-on-write copies)"),
            "evictions": reg.counter(
                "fleet_prefix_evictions_total",
                help="prefix-cache entries evicted (LRU under page "
                     "pressure or index capacity)")}
        self._prefix_seen = {}   # name -> last folded stat values
        self._fpsets = {}        # name -> (fingerprint set, page_size)
        self._m_pfx_hitp = {}
        self._m_pfx_pages = {}
        # speculative-decoding acceptance telemetry: same heartbeat
        # delta-fold discipline as the prefix counters (registered at
        # 0 so a cold fleet exports the catalogue; a replica restart
        # folds the new absolute value, never a negative delta)
        self._m_spec = {
            "proposed": reg.counter(
                "fleet_spec_proposed_total",
                help="draft tokens dispatched to speculative verify "
                     "across the fleet (folded from heartbeats)"),
            "accepted": reg.counter(
                "fleet_spec_accepted_total",
                help="draft tokens the target models confirmed — "
                     "committed bit-identical to plain decode"),
            "dispatches": reg.counter(
                "fleet_spec_dispatches_total",
                help="folded verify dispatches across the fleet")}
        self._spec_seen = {}     # name -> last folded spec stats
        self._m_spec_drafted = {}
        self._m_spec_acc = {}
        # -- continuous profiling plane (observability.contprof): the
        # router samples its OWN control loop (placement/journal
        # phases) when armed, and folds every replica heartbeat's
        # profile digest into a fleet hotspot rollup (health()) plus
        # fleet_profile_* counters — the same restart-tolerant
        # delta-fold discipline as the prefix/spec sections above.
        self._m_profile = {
            "samples": reg.counter(
                "fleet_profile_samples_total",
                help="host stack samples folded across replica "
                     "continuous profilers (from heartbeats)"),
            "dropped": reg.counter(
                "fleet_profile_samples_dropped_total",
                help="replica profile samples truncated at the "
                     "profile-trie node bound — caps are never "
                     "silent"),
            "backoffs": reg.counter(
                "fleet_profile_backoffs_total",
                help="replica profiler Hz halvings taken to stay "
                     "under the overhead cap")}
        self._profile_seen = {}     # name -> last folded stat values
        self._profile_digests = {}  # name -> last heartbeat digest
        # -- device-memory plane (observability.memledger): replica
        # heartbeats carry the ledger digest; stats delta-fold into
        # fleet_mem_* counters, the latest digests feed the
        # MEM%/HEADROOM rollup (health() / fleet_top) and the
        # mem_headroom placement term. The unattributed gauge is the
        # fleet canary's leak tripwire (worst replica wins).
        self._m_mem = {
            "tracked_allocs": reg.counter(
                "fleet_mem_tracked_allocs_total",
                help="allocations attributed through replica memory "
                     "ledgers (folded from heartbeats)"),
            "released_allocs": reg.counter(
                "fleet_mem_released_allocs_total",
                help="tracked allocations released across the fleet"),
            "admission_checks": reg.counter(
                "fleet_mem_admission_checks_total",
                help="would_fit admission hints consulted across the "
                     "fleet"),
            "admission_rejections": reg.counter(
                "fleet_mem_admission_rejections_total",
                help="admissions replica ledgers judged would not "
                     "fit the forecast headroom"),
            "audit_failures": reg.counter(
                "fleet_mem_audit_failures_total",
                help="ledger sweep audit problems across the fleet "
                     "(prefix refcount divergence and kin)")}
        self._m_mem_unattr = reg.gauge(
            "fleet_mem_unattributed_bytes",
            help="largest per-replica unattributed device-memory "
                 "residual (the leak canary: attribution drift is "
                 "visible fleet-wide, never silent)")
        self._mem_seen = {}      # name -> last folded stat values
        self._mem_digests = {}   # name -> last heartbeat mem digest
        if profile is None:
            profile = os.environ.get(
                "PADDLE_TPU_PROFILE", "0").lower() in ("1", "true",
                                                       "on")
        self.profiler = None
        if profile:
            from ..observability.contprof import ContinuousProfiler
            self.profiler = ContinuousProfiler(
                hz=profile_hz, registry=reg, name="router").start()

    def _new_client(self, rep):
        seed = self._next_client_seed
        self._next_client_seed += 1
        return ReplicaClient(rep, retries=self._transport_retries,
                             jitter=self._retry_jitter,
                             jitter_seed=seed)

    # -- metric series (lazy per label) -----------------------------------

    def _labeled(self, cache, name, help, **labels):
        return labeled_counter(self.registry, cache, name, help,
                               **labels)

    def _req_counter(self, status):
        return self._labeled(
            self._m_req, "fleet_requests_total",
            "resolved fleet requests by terminal status", status=status)

    def _routed_counter(self, replica):
        return self._labeled(
            self._m_routed, "fleet_routed_total",
            "requests placed, per replica", replica=replica)

    def _failover_counter(self, replica, reason):
        return self._labeled(
            self._m_failover, "fleet_failovers_total",
            "in-flight requests recovered off a lost replica",
            replica=replica, reason=reason)

    def _hedge_win_counter(self, by):
        return self._labeled(
            self._m_hedge_wins, "fleet_hedge_wins_total",
            "hedged requests by which leg finished first", by=by)

    def _bclamp_counter(self, tenant):
        return self._labeled(
            self._m_bclamp, "fleet_brownout_clamped_total",
            "requests whose decode budget was clamped by the "
            "brownout ladder, per tenant", tenant=tenant)

    # -- public API --------------------------------------------------------

    def submit(self, prompt, max_new_tokens=16, eos_token_id=None,
               priority=0, deadline_ms=None, tenant=None):
        """Accept one request into the fleet; returns its fleet rid.
        Placement happens at the next step().

        deadline_ms: wall budget from NOW for the whole fleet journey
        (placement + every leg). The REMAINING budget rides each
        placement, so a failover continuation inherits what is left,
        and a request that expires while queued at the router resolves
        status='expired' without ever placing.

        tenant: usage-attribution label (observability.tenancy). It
        rides every placement down to the engine (which accounts
        queue-wait and KV-page-seconds), the router accounts fleet
        token totals per tenant at resolve, /tenants serves the
        heavy-hitter rollup, and the shed order prefers shedding the
        heaviest tenants within a priority band. None lands under
        "anon" in the fleet rollup.

        Every submit mints a distributed-trace context: the request's
        span tree (placement wait, transport, per-replica legs with
        their queue/prefill/decode, failover/hedge annotations) lands
        in the trace store — read it back via ``trace_report(rid)`` or
        the ``/traces`` endpoint.

        With a journal, admission is write-ahead: the ``accepted``
        record lands durably BEFORE the rid is registered, and a disk
        failure (``journal_io_error``) rejects the submit with
        JournalError — the caller knows the request was never
        accepted, instead of discovering after a crash that it was
        never recoverable."""
        if self._closed:
            raise RuntimeError("FleetRouter is closed")
        rid = self._next_rid
        self._next_rid += 1
        deadline = None if deadline_ms is None \
            else time.monotonic() + float(deadline_ms) / 1e3
        p = _Pending(rid, prompt, max_new_tokens, eos_token_id,
                     priority, deadline=deadline, tenant=tenant)
        if self._journal is not None:
            self._journal.append(
                "accepted", rid=rid, prompt=p.prompt,
                max_new=p.max_new, eos=p.eos, priority=p.priority,
                tenant=p.tenant,
                deadline_epoch=None if deadline_ms is None
                else round(time.time() + float(deadline_ms) / 1e3, 6),
                submitted_epoch=round(time.time(), 6))
        # traffic capture decides BEFORE the trace mints: a captured
        # request force-keeps its span tree (whole-tree head sampling
        # stays coherent with capture sampling — an archived request
        # always carries its attribution)
        if self.recorder is not None and self.recorder.admit():
            p.captured = self.recorder.record_arrival(
                rid, p.prompt, p.max_new, eos=p.eos,
                priority=p.priority, tenant=p.tenant,
                deadline_ms=deadline_ms, t_pc=p.queue_since_pc)
        p.trace = self._tstore.new_trace(
            name="request", proc="router", rid=rid,
            args={"prompt_len": len(p.prompt), "max_new": p.max_new,
                  "priority": p.priority},
            force=p.captured is not None)
        if p.trace is not None:
            self._trace_ids.append(p.trace["trace_id"])
        self._pending[rid] = p
        self._queue.append(rid)
        return rid

    def step(self):
        """One control round: harvest results, scrape health, fail
        over lost replicas, expire/place/shed/hedge, evaluate SLO
        burn. Returns the results resolved this round — a PREVIEW:
        with a journal, exactly-once delivery across a crash holds
        only for results consumed via results()/run_to_completion()
        (the retire-before-handout edge); a previewed-but-unpopped
        result is re-delivered by a successor. An unhandled exception
        here is a flight-recorder trigger
        (flight_fleet_router_exception.json) — the postmortem carries
        the fleet registry and recent fleet events."""
        if self._closed:
            raise RuntimeError("FleetRouter is closed")
        try:
            return self._step_impl()
        except Exception as e:
            from ..observability import flightrec
            flightrec.dump("fleet_router_exception", extra={
                "error": f"{type(e).__name__}: {e}",
                "fleet_registry": self._registry_snapshot()})
            raise

    def _step_impl(self):
        self._step_n += 1
        if faults.pull("router_crash", self._step_n) is not None:
            raise RouterCrash(
                f"injected router_crash (control round {self._step_n})")
        # preemption (SIGTERM grace window): the replicas drain
        # themselves through the same seam — the ROUTER's job is to
        # seal the journal so its successor finds a complete, not
        # torn, tail. Results resolving inside the grace window keep
        # journaling after the seal; the seal is the "tail is clean
        # as of the notice" claim
        if self._journal is not None and not self._journal.sealed \
                and preemption.requested():
            try:
                self._flush_jbacklog()
                self._jappend("preempt")
                self._journal.seal()
            except JournalCrash:
                raise
            except JournalError:
                pass   # transient: sealed stays False — the next
                #        control round retries, the drain continues
        self._flush_jbacklog()
        before = set(self._done)
        self._collect()
        self._scrape_all()
        self._recover_lost()
        self._expire_queued()
        self._place()
        self._overload_control()
        self._shed()
        self._hedge()
        if self._journal is not None and self._journal.needs_rotation:
            self._journal.rotate(self._snapshot_records(),
                                 next_rid=self._next_rid)
        self._g_queue.set(len(self._queue))
        self._g_pending.set(
            sum(1 for p in self._pending.values() if not p.done))
        self._g_serving.set(len(self._serving_candidates()))
        out = [self._done[r] for r in self._done if r not in before]
        # SLO state refreshes when something actually resolved (the
        # events that move the windows) or on a coarse heartbeat —
        # never per idle 2ms poll round, where the window scans would
        # dominate the control loop
        now = time.monotonic()
        if self.slo is not None and (
                out or now - self._slo_eval_at > 0.25):
            self._slo_state = self.slo.evaluate()
            self._slo_eval_at = now
        # history scrape + anomaly evaluation ride the SAME control
        # loop on their own cadences (maybe_* no-op between ticks) —
        # scrape first so the sentinel reads the freshest samples
        if self.history is not None:
            self.history.maybe_scrape()
        if self.sentinel is not None:
            st = self.sentinel.maybe_evaluate()
            if st is not None:
                self._anomaly_state = st
        return out

    def _registry_snapshot(self):
        try:
            return self.registry.snapshot()
        except Exception:  # noqa: BLE001 — postmortem best-effort
            return None

    def run_to_completion(self, timeout_s=120.0, poll_s=0.002):
        """Drive step() until every accepted request resolves; returns
        all results in rid order (cleared from the done buffer). A
        transiently-withheld pop (results() returning [] because the
        `retired` journal record hit a disk blip) is retried until
        the timeout — resolved results are never silently dropped."""
        t_end = time.monotonic() + float(timeout_s)
        while any(not p.done for p in self._pending.values()):
            self.step()
            if not any(not p.done for p in self._pending.values()):
                break
            if time.monotonic() > t_end:
                stuck = sorted(r for r, p in self._pending.items()
                               if not p.done)
                raise RuntimeError(
                    f"fleet did not drain within {timeout_s}s; "
                    f"unresolved rids: {stuck[:10]}")
            time.sleep(poll_s)
        out = self.results()
        while self._done:
            if time.monotonic() > t_end:
                raise RuntimeError(
                    f"journal withheld {len(self._done)} resolved "
                    f"results past the {timeout_s}s deadline (retired "
                    "record not durable)")
            time.sleep(poll_s)
            out += self.results()
        return out

    def results(self):
        """Pop resolved results, rid order. Popping also retires the
        router-side request state: a long-lived router stays bounded
        by its in-flight window, not its lifetime request count (rids
        never repeat, so a stray late result for a retired rid simply
        finds no pending entry and is dropped — the same dedup as
        before, without the unbounded table).

        With a journal, the pop is journaled (``retired``) BEFORE the
        results are handed over: a recovered router re-delivers only
        results the dead incarnation never handed out — exactly-once
        across the crash, at-most-once on this edge. A transient disk
        failure on that append WITHHOLDS the results (returns []) —
        they stay in the done buffer and deliver on a later call once
        the journal accepts the retirement record; handing them over
        un-retired would re-deliver them after a crash."""
        out = [self._done[r] for r in sorted(self._done)]
        if out and self._journal is not None:
            self._flush_jbacklog()
            if self._jbacklog:
                return []   # order: `retired` must not jump parked
                #             records for the same rids (see _jappend)
            try:
                self._journal.append("retired",
                                     rids=[r["id"] for r in out])
            except JournalCrash:
                raise
            except JournalError:
                return []
        for r in self._done:
            self._pending.pop(r, None)
        self._done = {}
        return out

    def generate(self, prompts, max_new_tokens=16, eos_token_id=None):
        """Convenience batch API (mirrors ServingEngine.generate):
        submit all, drain the fleet, return token lists in submission
        order."""
        ids = [self.submit(p, max_new_tokens, eos_token_id)
               for p in prompts]
        res = {r["id"]: r for r in self.run_to_completion()}
        return [res[i]["tokens"] for i in ids]

    def drain(self, name):
        """Gracefully drain one replica (same seam a preemption notice
        uses): stops admitting, finishes in-flight, bounces queued
        work back for re-placement."""
        self.replicas[name].drain()

    def rejoin(self, name):
        """Bring a drained/failed replica back into rotation. For an
        in-process replica this restarts the worker on the SAME engine
        (zero recompiles); for a process replica it is a respawn — a
        fresh incarnation that warm-boots before accepting traffic."""
        self.replicas[name].rejoin()
        self.reinstate(name)

    def reinstate(self, name):
        """Dynamic-membership half of a rejoin: clear the lost mark
        and the stale scrape so the next control round can route to
        `name` again. The FleetSupervisor calls THIS after it already
        respawned the replica and health-gated its warm boot — the
        router must not respawn a second time."""
        if name not in self.replicas:
            raise KeyError(f"unknown replica {name!r}")
        self._lost.discard(name)
        self._last_scrape.pop(name, None)

    def adopt_replica(self, rep):
        """Dynamic membership: add a NEW replica to the live fleet
        (placement picks it up once its first heartbeat lands). The
        name must be new — a respawned same-name replica keeps its
        transport object and goes through reinstate()."""
        if rep.name in self.replicas:
            raise ValueError(f"replica {rep.name!r} already in the "
                             "fleet (respawns go through reinstate)")
        self.replicas[rep.name] = rep
        self._clients[rep.name] = self._new_client(rep)

    def retire(self, name):
        """Begin a graceful scale-in of `name` (the autoscaler's
        drain half). Before the drain, any HEDGE leg parked on the
        victim is cancelled and folded closed: a duplicate leg whose
        primary still runs elsewhere must not keep decoding on a
        draining replica — it would burn a slot for tokens the
        stale-leg guard (or the first-finisher dedup) was always
        going to discard, delaying the drain by a full decode. The
        replica is removable (``remove_replica``) once drained and
        its assignments have resolved."""
        rep = self.replicas.get(name)
        if rep is None:
            raise KeyError(f"unknown replica {name!r}")
        self._cancel_stray_hedges(name)
        rep.drain()

    def _cancel_stray_hedges(self, name):
        """Cancel hedge legs parked on `name` whose primary leg still
        runs elsewhere, and close them in the trace tree WITHOUT a
        failover (nothing needs recovering — the primary owns the
        request). The engine resolves the cancel with partial tokens;
        p.hedge is cleared NOW so _handle's stale-leg guard drops that
        flush instead of folding it."""
        for rid, p in list(self._pending.items()):
            if p.done or p.hedge != name or p.replica is None:
                continue
            try:
                self._clients[name].cancel(rid)
            except Exception:  # noqa: BLE001 — replica may be gone
                pass
            self._end_leg(p, name, "cancelled", scale_in=True)
            p.hedge = None
            p.leg_base.pop(name, None)
            p.leg_inc.pop(name, None)

    def remove_replica(self, name):
        """Dynamic membership: retire a replica from the fleet. Any
        in-flight hedge leg whose primary still runs is cancelled
        (never failed over — the primary owns it), then unresolved
        assignments fail over (prefix-deduped, same path as a crash),
        so nothing is lost — but the replica must already be out of
        service (lost, dead, drained or quarantined); drain it first
        (``retire``) for a graceful exit."""
        rep = self.replicas.get(name)
        if rep is None:
            raise KeyError(f"unknown replica {name!r}")
        if rep.alive and rep.state not in ("drained",) \
                and name not in self._lost \
                and not getattr(rep, "quarantined", False):
            raise RuntimeError(
                f"replica {name!r} is still in service "
                f"(state={rep.state}); drain it first")
        self._cancel_stray_hedges(name)
        self._recover_assignments(name, "removed", rep)
        del self.replicas[name]
        del self._clients[name]
        self._lost.discard(name)
        self._last_scrape.pop(name, None)

    def journal_event(self, kind, **fields):
        """Journal one control-plane decision record (``scale_out`` /
        ``scale_in`` — the FleetAutoscaler's write path into the same
        WAL the request lifecycle uses). Rides the ordered backlog
        like any lifecycle record; a journal-less router no-ops.
        Returns False while the record is parked (transient disk
        fault), True once durable."""
        self._scale_log.append(dict(fields, kind=str(kind)))
        return self._jappend(str(kind), **fields)

    def cancel(self, rid):
        """Cancel a fleet request wherever it currently lives. The
        intent is journaled (retried from the backlog on a transient
        disk blip), so a router crash between accepting the cancel
        and resolving it normally resolves the request cancelled at
        recovery instead of spending the remaining decode budget. A
        crash INSIDE the retry window can still lose the intent —
        the request then resolves ``ok``, indistinguishable from a
        cancel that lost its (inherent) race with completion."""
        p = self._pending.get(rid)
        if p is None or p.done:
            return False
        self._cancel_requested.add(rid)
        self._jappend("cancel", rid=rid)
        if rid in self._queue:
            self._queue.remove(rid)
            self._resolve(p, list(p.delivered), "cancelled", None)
            return True
        for name in (p.replica, p.hedge):
            if name is not None and name in self._clients:
                try:
                    self._clients[name].cancel(rid)
                except Exception:  # noqa: BLE001 — transport gave up
                    pass
        return True

    def health(self):
        """Fleet-wide snapshot: per-replica state + last scrape age,
        queue/pending depth, lost set. What an operator (or an outer
        LB) pages on."""
        now = time.monotonic()
        reps = {}
        for name, rep in self.replicas.items():
            snap = self._last_scrape.get(name)
            reps[name] = {
                "alive": rep.alive, "state": rep.state,
                "lost": name in self._lost,
                "incarnation": getattr(rep, "incarnation", None),
                "quarantined": bool(getattr(rep, "quarantined",
                                            False)),
                "scrape_age_s": (None if snap is None
                                 else round(now - snap["ts"], 6)),
                "queued": snap.get("queued") if snap else None,
                "running": snap.get("running") if snap else None,
                "free_pages": snap.get("free_pages") if snap else None,
                "boot": snap.get("boot") if snap else None,
                "error": rep.error}
        # list() snapshots: health() also runs on metrics-exporter
        # HTTP threads, and the control thread may be mid-submit
        asc = self.autoscaler
        return {"replicas": reps,
                "queue_depth": len(self._queue),
                "pending": sum(1 for p in list(self._pending.values())
                               if not p.done),
                "lost": sorted(self._lost),
                "slo": self._slo_health(),
                "anomaly": self._anomaly_health(),
                "overload": self._overload_health(),
                # the autoscaler's cached rollup (updated on its
                # poll(); health() also runs on HTTP threads, so this
                # must stay a cheap dict read)
                "autoscale": None if asc is None else asc.snapshot(),
                "tenants": None if self.tenants is None else {
                    "tracked": self.tenants.tracked},
                # fleet hotspot rollup off cached heartbeat digests
                # (plus the router's own profiler when armed) — cheap
                # dict folds only, same HTTP-thread discipline
                "profile": self._profile_health(),
                # device-memory rollup off cached heartbeat ledger
                # digests (_fold_mem keeps them fresh) — same
                # cheap-dict-read discipline
                "mem": self._mem_health(),
                "compile_report": self.compile_report()}

    def _mem_health(self):
        """Fleet device-memory rollup for the health snapshot:
        per-replica used/headroom/residual off the cached heartbeat
        ledger digests, plus fleet-merged segment totals. Cached-read
        only (health() also runs on HTTP threads); None when no
        replica has an armed ledger — the dormancy contract reaches
        the fleet rollup too."""
        digests = dict(self._mem_digests)
        if not digests:
            return None
        segments = {}
        per_replica = {}
        worst_unattr = 0
        for name, dg in digests.items():
            for seg, n in (dg.get("segments") or {}).items():
                segments[seg] = segments.get(seg, 0) + int(n)
            un = dg.get("unattributed_bytes")
            if un is not None:
                worst_unattr = max(worst_unattr, int(un))
            per_replica[name] = {
                "used_bytes": dg.get("used_bytes"),
                "used_ratio": dg.get("used_ratio"),
                "headroom_bytes": dg.get("headroom_bytes"),
                "unattributed_bytes": un,
                "growth_bytes_per_s": dg.get("growth_bytes_per_s"),
                "residual_alarm": bool(dg.get("residual_alarm")),
                "audit_problems": list(dg.get("audit_problems")
                                       or [])}
        return {"segments": segments,
                "unattributed_bytes_max": worst_unattr,
                "replicas": per_replica}

    def _profile_health(self):
        """Fleet hotspot rollup for the health snapshot: per-phase
        sample shares summed across the cached replica heartbeat
        digests (_fold_profile keeps them fresh), merged top frames,
        and per-replica host duty (HOST% = 100*(1-idle share) — how
        much of the host's sampled time was NOT idle). Cached-read
        only: health() also runs on HTTP threads."""
        digests = dict(self._profile_digests)
        if self.profiler is None and not digests:
            return None
        phases = {}
        frames = {}
        per_replica = {}
        for name, dg in digests.items():
            for ph, n in (dg.get("phases") or {}).items():
                phases[ph] = phases.get(ph, 0) + int(n)
            for rows in (dg.get("top") or {}).values():
                for fr, n in rows:
                    frames[fr] = frames.get(fr, 0) + int(n)
            total = sum(int(n) for n in (dg.get("phases")
                                         or {}).values())
            idle = int((dg.get("phases") or {}).get("idle", 0))
            per_replica[name] = {
                "samples": int(dg.get("samples") or 0),
                "dropped": int(dg.get("dropped") or 0),
                "overhead_ratio": dg.get("overhead_ratio"),
                "hz": dg.get("hz"),
                "host_pct": (None if not total else
                             round(100.0 * (1.0 - idle / total), 1))}
        out = {"phases": phases,
               "top": dict(sorted(frames.items(),
                                  key=lambda kv: -kv[1])[:8]),
               "replicas": per_replica}
        if self.profiler is not None:
            out["router"] = self.profiler.digest()
        return out

    def _anomaly_health(self):
        """Sentinel rollup for the health snapshot — same shape and
        same caching discipline as the SLO rollup (health() also runs
        on HTTP threads; the sentinel evaluates on the control loop,
        this just reads the cached state)."""
        if self.sentinel is None:
            return None
        state = self._anomaly_state
        return {"alerting": sorted(n for n, r in state.items()
                                   if r.get("alert")),
                "signals": {n: {"alert": r.get("alert", False),
                                "value": r.get("value"),
                                "z": r.get("z")}
                            for n, r in state.items()}}

    def _slo_health(self):
        """Burn state for the health snapshot (cached from the last
        step()'s evaluation — health() also runs on HTTP threads and
        must stay cheap): per-objective alert flags + SLIs, so
        placement or an outer LB can see budget burn without scraping
        the gauge series."""
        if self.slo is None:
            return None
        state = self._slo_state
        return {"alerting": sorted(n for n, r in state.items()
                                   if r.get("alert")),
                "objectives": {n: {"alert": r.get("alert", False),
                                   "sli": r.get("sli"),
                                   "events": r.get("events", 0)}
                               for n, r in state.items()}}

    def compile_report(self):
        """Per-replica compile counts + fleet-wide unexpected-retrace
        total — the zero-recompile assertion's fleet form (must stay
        frozen through crash/drain/rejoin waves)."""
        reps = {}
        unexpected = 0
        for name, rep in self.replicas.items():
            # transport verbs, not engine reads: a ProcReplica's
            # engine lives in another process — its counts arrive on
            # the heartbeat plane
            if hasattr(rep, "compile_counts"):
                reps[name] = rep.compile_counts()
                unexpected += rep.unexpected_retraces()
            else:
                reps[name] = rep.engine.compile_counts()
                unexpected += rep.engine.tracer.unexpected_retraces()
        return {"replicas": reps, "unexpected_retraces": unexpected}

    def trace_report(self, rid):
        """Per-request latency attribution: the span tree plus the
        hop-by-hop decomposition whose coverage must reach the
        end-to-end wall time within ``attribution_tolerance``.
        Works while the request is live AND after it resolved (until
        the trace evicts from the store); None for unknown rids."""
        p = self._pending.get(rid)
        tid = p.trace["trace_id"] if p is not None \
            and p.trace is not None else self._tstore.find(rid)
        if tid is None:
            return None
        return {"trace": self._tstore.tree(tid),
                "attribution": self._tstore.attribution(
                    tid, tolerance=self.attribution_tolerance)}

    def _traces_endpoint(self, key):
        """The /traces handler: index of known traces (one cheap
        store pass — a periodic scraper must not contend the control
        loop on the store lock; fetch /traces/<rid> for the full
        attribution), or one trace's report by fleet rid (digits) /
        trace id."""
        if key is None:
            return {"traces": self._tstore.summaries(),
                    "tolerance": self.attribution_tolerance}
        if str(key).isdigit():
            return self.trace_report(int(key))
        tree = self._tstore.tree(key)
        if tree is None:
            return None
        return {"trace": tree,
                "attribution": self._tstore.attribution(
                    key, tolerance=self.attribution_tolerance)}

    def _requests_endpoint(self, key):
        """The /requests handler: recent-resolved index (one cheap
        deque copy — rid, tenant, status, ttft/e2e, archive locator;
        the /traces index's request-plane sibling), or one row by
        fleet rid. fleet_top and the replay tool locate a request
        here instead of scanning archives."""
        # the handler runs on exporter HTTP threads while the control
        # thread appends — copying a deque mid-append can raise
        # "mutated during iteration"; one retry makes the race benign
        try:
            rows = list(self._requests_index)
        except RuntimeError:
            rows = list(self._requests_index)
        if key is None:
            return {"requests": rows,
                    "capture": None if self.recorder is None else {
                        "dir": self.recorder.dir,
                        "sample": self.recorder.sample}}
        if not str(key).isdigit():
            return None
        rid = int(key)
        for row in reversed(rows):
            if row["rid"] == rid:
                return row
        return None

    def export_timeline(self, path, extra_recorders=()):
        """Merge every trace this router minted (bounded to the last
        512) into ONE Perfetto timeline: a router lane plus one lane
        per replica, per-process clock offsets reconciled from the
        heartbeat estimates. Pass engine SpanRecorders (or the train/
        profiler ones) as extra_recorders to overlay the round-10
        lanes — everything shares the epoch base. Returns the path."""
        return self._tstore.export_chrome(
            path, trace_ids=list(self._trace_ids),
            clock_offsets=dict(self._clock_offsets),
            extra_recorders=extra_recorders)

    def serve_metrics(self, port=0, host="127.0.0.1"):
        """Attach a live HTTP exporter to the ROUTER with full
        endpoint parity with its replicas: /metrics is the fleet
        registry (incl. the fleet_slo_* gauges), /healthz the fleet
        health rollup, /report the fleet compile report on top of the
        process recompile/cost reports, /traces the per-request
        latency-attribution reports. The router is a scrape target
        just like its replicas — same exporter, no bespoke handler."""
        from ..observability.exporter import MetricsExporter
        if self._exporter is not None:
            self._exporter.close()
        self._exporter = MetricsExporter(
            registry=self.registry, port=port, host=host,
            health_fn=self.health,
            report_fn=lambda: {"fleet_compile_report":
                               self.compile_report()},
            traces_fn=self._traces_endpoint,
            requests_fn=self._requests_endpoint,
            history_fn=None if self.history is None
            else self._history_endpoint,
            tenants_fn=None if self.tenants is None
            else self.tenants.report,
            profile_fn=None if self.profiler is None
            else (lambda window: self.profiler.report(window_s=window)),
            # /memory on the router serves the fleet rollup (cached
            # heartbeat ledger digests); a ledger-less fleet answers
            # the same stub shape an unarmed engine does
            memory_fn=lambda window: (
                self._mem_health()
                or {"armed": False,
                    "note": "no replica ledger armed "
                            "(PADDLE_TPU_MEM_LEDGER=1)"}))
        return self._exporter

    def _history_endpoint(self, params):
        """The /history handler: bare GET = the series index; with
        ``series=`` a range read (``res``/``t0``/``t1``/``limit``) or
        a server-side rollup (``op=rate|quantile`` with ``window``/
        ``q``) — tools/fleet_top.py's data plane. Unknown series ->
        None -> 404."""
        h = self.history
        key = (params or {}).get("series")
        if not key:
            return {"series": h.index(),
                    "interval_s": h.interval_s,
                    "scrapes": h.scrapes,
                    "rungs": [list(r) for r in h.rungs]}
        if key not in h.keys():
            return None
        op = params.get("op", "query")
        window = float(params.get("window", 30.0))
        if op == "rate":
            return {"series": key, "op": "rate", "window_s": window,
                    "value": h.rate(key, window)}
        if op == "quantile":
            q = float(params.get("q", 0.99))
            return {"series": key, "op": "quantile", "q": q,
                    "window_s": window,
                    "value": h.quantile_over_time(key, q, window)}
        t0 = params.get("t0")
        t1 = params.get("t1")
        limit = params.get("limit")
        return {"series": key, "res": params.get("res", "raw"),
                "samples": h.query(
                    key, t0=None if t0 is None else float(t0),
                    t1=None if t1 is None else float(t1),
                    res=params.get("res", "raw"),
                    limit=None if limit is None else int(limit))}

    def close(self):
        """Stop every replica worker and the exporter. Engines are
        NOT closed (the router does not own them); idempotent."""
        if self._closed:
            return
        self._closed = True
        for rep in self.replicas.values():
            rep.kill()
        if self._journal is not None:
            try:
                self._flush_jbacklog()
            except JournalError:  # incl. JournalCrash — closing anyway
                pass
            self._journal.close()
        if self.history is not None:
            self.history.stop()   # no-op unless start() armed a thread
        if self.recorder is not None:
            self.recorder.close()  # finalize the active segment so a
            #                        closed archive replays drop-free
        if self._exporter is not None:
            self._exporter.close()
            self._exporter = None
        if self.profiler is not None:
            self.profiler.stop()

    # -- control-plane internals --------------------------------------------

    def _handle_batch(self, batch, ack_fn):
        """Process harvested results and ack the ones whose handling
        is durable. Ack = "processed AND (when journaling) the
        `resolved` record landed": a result whose terminal record is
        still in the retry backlog stays retained at the replica, so
        a crash inside the durability gap re-surfaces it to the
        successor instead of losing it. Re-polled already-processed
        results dedup in _handle and ack here (the retry path for a
        lost ack)."""
        acks = []
        for res in batch:
            self._handle(res)
            rseq = res.get("_rseq")
            if rseq is not None \
                    and res["id"] not in self._junacked_rids:
                acks.append(rseq)
        if not acks:
            return
        try:
            ack_fn(acks)
        except Exception:  # noqa: BLE001 — retained results simply
            pass           # re-poll next round; _handle dedups

    def _collect(self):
        for name in self.replicas:
            try:
                batch = self._clients[name].poll()
            except Exception:  # noqa: BLE001 — transport gave up; retry
                continue       # next round (results stay queued)
            self._handle_batch(batch, self._clients[name].ack)

    def _handle(self, res):
        rid = res["id"]
        p = self._pending.get(rid)
        if p is None or p.done:
            return  # stray: hedge loser, post-rejoin flush — dedup
        src = res.get("replica")
        status = res["status"]
        if src is not None and src not in (p.replica, p.hedge):
            # stale leg: a rejoined replica flushing its pre-crash
            # slot, a late result from a replica this rid was already
            # failed over FROM, or a recovery-distrusted placement.
            # Its token stream is relative to a prefix this router no
            # longer tracks — folding or stitching it could corrupt
            # the client's stream (duplicate or gap the prefix of a
            # resubmit already running elsewhere). Drop it; the live
            # leg resolves the rid
            return
        inc = res.get("incarnation")
        if src is not None and inc is not None \
                and p.leg_inc.get(src) is not None \
                and inc != p.leg_inc[src]:
            # stale INCARNATION: the rid was re-placed onto the same
            # replica NAME after a respawn/rejoin, and this result was
            # produced by the previous incarnation's engine (a flushed
            # pre-crash slot). Same-name placement used to let it pass
            # the src guard above; the incarnation stamp closes that —
            # uniformly, for every status
            return
        # every leg's tokens are relative to the delivered prefix it
        # was PLACED with — anchor all folds/stitches there, never at
        # whatever delivered has since become (a continuation leg that
        # outlives a second failover, or a hedge racing a bounced
        # primary, would otherwise duplicate or drop the middle)
        base = p.leg_base.get(src, len(p.delivered))
        unsolicited_cancel = (status == "cancelled"
                              and rid not in self._cancel_requested)
        if status == "bounced" or unsolicited_cancel:
            # drain bounce: the replica gave the request back — keep
            # the longest ABSOLUTE token prefix seen and re-place
            self._end_leg(p, src, "bounced",
                          tokens=len(res.get("tokens") or []))
            cand = p.delivered[:base] \
                + [int(t) for t in res.get("tokens") or []]
            if len(cand) > len(p.delivered):
                p.delivered = cand
                # delivered-prefix watermark: the dedup boundary a
                # continuation (or a post-crash recovery) resubmits
                # from. Losing this record to a disk fault only costs
                # recomputation — greedy decoding regenerates the same
                # tokens — never correctness
                self._jappend("delivered", rid=rid, tokens=p.delivered)
            if src == p.replica:
                p.replica = None
            if src == p.hedge:
                p.hedge = None
            if p.replica is None and p.hedge is None \
                    and rid not in self._queue:
                self._m_requeued.inc()
                # back at the router as of NOW — whether it re-queues
                # or finishes straight from the prefix, the current
                # router-resident period starts here (a stale
                # queue_since_pc would make the resolve-time
                # router_queue hop overlap the leg it just finished)
                p.queue_since_pc = dtrace.now()
                if not self._finish_from_prefix(p):
                    self._queue.append(rid)
            return
        if status == "cancelled":
            # the cancel WE asked for. Hedge losers never reach this
            # (their rid is already done → dedup above); what remains
            # is a client-initiated cancel of a running request, which
            # resolves with its partial tokens
            self._cancel_requested.discard(rid)
            self._end_leg(p, src, "cancelled")
            self._resolve(
                p,
                p.delivered[:base] + list(res.get("tokens") or []),
                "cancelled", src, usage=self._usage_of(res))
            return
        # terminal: ok | expired — first finisher wins
        tokens = p.delivered[:base] + list(res.get("tokens") or [])
        if p.hedged and p.replica is not None and p.hedge is not None:
            loser = p.hedge if src == p.replica else p.replica
            by = "primary" if src == p.replica else "hedge"
            self._hedge_win_counter(by).inc()
            self._cancel_requested.add(rid)
            # the losing leg stays in the trace tree, annotated — the
            # postmortem sees what the hedge cost, not a missing span
            self._end_leg(p, loser, "cancelled", hedge_loser=True)
            try:
                self._clients[loser].cancel(rid)
            except Exception:  # noqa: BLE001 — loser may already be gone
                pass
        self._end_leg(p, src, status,
                      tokens=len(res.get("tokens") or []))
        self._resolve(p, tokens, status, src, usage=self._usage_of(res))

    @staticmethod
    def _usage_of(res):
        """Engine-side usage facts riding a replica result (what only
        the engine can see: admission queue wait, KV-page-seconds) —
        folded into the per-tenant sketch at resolve."""
        return {"queue_wait_s": res.get("queue_wait_s"),
                "kv_page_s": res.get("kv_page_s"),
                "prefix_hit_pages": res.get("prefix_hit_pages"),
                "prefix_pages": res.get("prefix_pages"),
                "spec_proposed": res.get("spec_proposed"),
                "spec_accepted": res.get("spec_accepted")}

    def _finish_from_prefix(self, p):
        """A recovered prefix may already satisfy the request (eos
        seen, or budget exhausted) — resolve without resubmitting.
        Returns True when resolved."""
        d = p.delivered
        if p.eos is not None and p.eos in d:
            self._resolve(p, d[:d.index(p.eos) + 1], "ok", None)
            return True
        if len(d) >= p.max_new:
            self._resolve(p, d[:p.max_new], "ok", None)
            return True
        return False

    def _resolve(self, p, tokens, status, replica, usage=None):
        age = time.monotonic() - p.submitted_at
        result = {
            "id": p.rid, "tokens": [int(t) for t in tokens],
            "status": status, "replica": replica,
            "failovers": p.failovers, "hedged": p.hedged,
            "tenant": p.tenant,
            "trace_id": None if p.trace is None
            else p.trace["trace_id"],
            "age_s": round(age, 6)}
        # WAL: the terminal record goes first. A JournalCrash here
        # (torn write = process death) leaves the request UNresolved
        # in memory and on disk — the successor re-resolves it exactly
        # once. A transient failure parks the record in the retry
        # backlog and blocks the replica-side ack until durable; a
        # non-empty backlog queues this record behind it (order —
        # see _jappend).
        if self._journal is not None \
                and not self._jappend("resolved", result=result):
            # gate the ack only while THIS record is still parked (a
            # queued-behind append may have flushed on the way)
            if any(k == "resolved" and f["result"]["id"] == p.rid
                   for k, f in self._jbacklog):
                self._junacked_rids.add(p.rid)
        p.done = True
        self._cancel_requested.discard(p.rid)
        self._req_counter(status).inc()
        # a request resolving with nothing running (shed, expired in
        # the router queue, finished straight from a recovered prefix)
        # spent its tail sitting at the ROUTER — record that wait as a
        # hop, so attribution still tiles e2e instead of reporting the
        # whole queue time as unattributed
        if p.replica is None and p.hedge is None and not p.leg_ctxs:
            self._tstore.add_span(p.trace, "router_queue",
                                  p.queue_since_pc, proc="router",
                                  args={"terminal": status})
        # close any leg a stray path left open, then the root — the
        # exported tree never carries a dangling open span for a
        # resolved request
        for name in list(p.leg_ctxs):
            self._end_leg(p, name, status)
        self._tstore.end_span(p.trace, outcome=status,
                              args={"tokens": len(tokens),
                                    "failovers": p.failovers,
                                    "hedged": p.hedged})
        ttft = self._ttft_from_trace(p) if status == "ok" else None
        self._record_slo(p, status, age, ttft)
        self._note_resolved(p, result, age, ttft)
        # fleet-level token/latency series + per-tenant attribution —
        # the history plane scrapes these, the sentinel bands them.
        # Counted at the SAME commit point, so sketch totals equal the
        # fleet counters exactly (the chaos wave's invariant)
        self._m_tokens_in.inc(len(p.prompt))
        self._m_tokens_out.inc(len(tokens))
        if status == "ok":
            self._m_e2e_h.observe(age)
            if ttft is not None:
                self._m_ttft_h.observe(ttft)
        u = usage or {}
        php = int(u.get("prefix_hit_pages") or 0)
        ppg = int(u.get("prefix_pages") or 0)
        spp = int(u.get("spec_proposed") or 0)
        spa = int(u.get("spec_accepted") or 0)
        if self.tenants is not None:
            self.tenants.account(
                p.tenant if p.tenant is not None else "anon",
                tokens_in=len(p.prompt), tokens_out=len(tokens),
                queue_wait_s=float(u.get("queue_wait_s") or 0.0),
                kv_page_s=float(u.get("kv_page_s") or 0.0),
                requests=1, prefix_hit_pages=php, prefix_pages=ppg,
                spec_proposed=spp, spec_accepted=spa)
        # per-tenant hit-rate series for the history plane / fleet_top
        # (pages, not requests: the rate that predicts TTFT savings)
        if ppg:
            tname = p.tenant if p.tenant is not None else "anon"
            self._labeled(
                self._m_pfx_pages, "fleet_prefix_pages_total",
                "shareable prompt pages of resolved requests, "
                "per tenant", tenant=tname).inc(ppg)
            if php:
                self._labeled(
                    self._m_pfx_hitp, "fleet_prefix_hit_pages_total",
                    "prompt pages served from a replica prefix cache, "
                    "per tenant", tenant=tname).inc(php)
        # per-tenant acceptance-rate series (fleet_top's SPEC_ACC):
        # drafted vs accepted tokens, the ratio that predicts decode
        # tok/s gains per tenant
        if spp:
            tname = p.tenant if p.tenant is not None else "anon"
            self._labeled(
                self._m_spec_drafted, "fleet_spec_draft_tokens_total",
                "draft tokens speculated for resolved requests, "
                "per tenant", tenant=tname).inc(spp)
            if spa:
                self._labeled(
                    self._m_spec_acc,
                    "fleet_spec_accepted_tokens_total",
                    "accepted draft tokens of resolved requests, "
                    "per tenant", tenant=tname).inc(spa)
        self._done[p.rid] = result

    def _note_resolved(self, p, result, age_s, ttft):
        """Post-resolve accounting for the capture plane: append the
        /requests index row (always — the index is how fleet_top and
        the replay tool locate a request without scanning archives)
        and, for captured requests, the ``resolve`` archive record
        with the compact per-hop attribution. A captured request that
        resolved without a span tree or attribution is a
        capture<->trace sampling divergence — counted, never
        silent."""
        hops = None
        if p.captured is not None and p.trace is not None:
            # attribution is O(spans) per request — paid only for
            # CAPTURED requests (the archive is what needs it; the
            # index row stays a one-pass cheap append)
            try:
                att = self._tstore.attribution(
                    p.trace["trace_id"],
                    tolerance=self.attribution_tolerance)
            except Exception:  # noqa: BLE001 — accounting only
                att = None
            if att is not None:
                hops = [{"name": h["name"], "proc": h["proc"],
                         "dur_s": h["dur_s"],
                         "outcome": h["outcome"]}
                        for h in att["hops"]]
        self._requests_index.append({
            "rid": p.rid, "tenant": p.tenant,
            "status": result["status"],
            "ttft_s": None if ttft is None else round(ttft, 6),
            "e2e_s": round(age_s, 6),
            "replica": result["replica"],
            "failovers": p.failovers, "hedged": p.hedged,
            "trace_id": result["trace_id"],
            "archive": None if p.captured is None
            else dict(p.captured),
            "ts": round(time.time(), 6)})
        if p.captured is None or self.recorder is None:
            return
        if hops is None:
            # divergence: counted via the recorder's PUBLIC surface
            # (capture= also accepts caller-supplied recorders)
            note = getattr(self.recorder, "note_trace_missing", None)
            if note is not None:
                note()
        self.recorder.record_resolve(
            p.rid, result["status"], result["tokens"],
            tenant=p.tenant, replica=result["replica"],
            failovers=p.failovers, hedged=p.hedged,
            e2e_s=age_s, ttft_s=ttft, hops=hops,
            trace_id=result["trace_id"])

    def _record_slo(self, p, status, age_s, ttft=None):
        """Fold one resolved request into the SLO windows: e2e
        latency, TTFT (read off the trace tree's first prefill leg),
        and goodput — shed + deadline-missed count against served;
        client-initiated cancels count as neither."""
        if self.slo is None:
            return
        if status == "ok":
            self.slo.record_event("availability", good=True)
            self.slo.record_latency("e2e", age_s)
            if ttft is not None:
                self.slo.record_latency("ttft", ttft)
        elif status in ("shed", "expired", "failed"):
            self.slo.record_event("availability", good=False)
            # a shed/expired request's latency is not a served
            # latency — the availability objective carries the miss

    def _ttft_from_trace(self, p):
        """submit -> first generated token, read as (end of the
        earliest prefill span) - (root start) across every leg of the
        trace. None when untraced or never prefilled."""
        if p.trace is None:
            return None
        root_t0, first = None, None
        for s in self._tstore.spans(p.trace["trace_id"]):
            if s["parent"] is None:
                root_t0 = s["t0"]
            elif s["name"].startswith("prefill") \
                    and s["t1"] is not None:
                if first is None or s["t1"] < first:
                    first = s["t1"]
        if root_t0 is None or first is None:
            return None
        return max(first - root_t0, 0.0)

    def _scrape_all(self):
        for name, rep in self.replicas.items():
            if name in self._lost:
                continue
            try:
                snap = rep.scrape()
            except Exception:  # noqa: BLE001 — scrape timeout: route stale
                self._m_scrape_errors.inc()
                continue
            if snap:
                self._last_scrape[name] = snap
                # the capture archive's replay-fidelity meta: each
                # replica's sampling params (temperature/top_k/seed)
                # ride its health plane — golden-mode replay asserts
                # token-exactness only when these match
                if self.recorder is not None \
                        and snap.get("sampling") is not None:
                    self.recorder.note_meta(**{
                        f"sampling.{name}": snap["sampling"]})
                # per-replica clock-skew upper bound from heartbeat
                # timestamps: receive_time - publish_ts >= |skew|, and
                # the min over many heartbeats approaches the true
                # one-way delay (+skew). In-process replicas share the
                # clock, so this stays ~0; the subprocess deployment
                # feeds it into the merged-timeline reconciliation.
                delay = max(time.monotonic() - snap["ts"], 0.0)
                prev = self._clock_offsets.get(name)
                self._clock_offsets[name] = delay if prev is None \
                    else min(prev, delay)
                self._fold_prefix(name, snap)
                self._fold_spec(name, snap)
                self._fold_profile(name, snap)
                self._fold_mem(name, snap)

    def _fold_profile(self, name, snap):
        """Harvest one heartbeat's continuous-profiler digest: cache
        the per-phase hotspot tables for the health() rollup and
        delta-fold the engine-monotonic sample stats into the
        fleet_profile_* counters (same restart tolerance as
        _fold_spec — a backwards value means the engine restarted,
        fold the new absolute, never a negative delta)."""
        pf = snap.get("profile")
        if not pf:
            self._profile_seen.pop(name, None)
            self._profile_digests.pop(name, None)
            return
        self._profile_digests[name] = pf
        seen = self._profile_seen.setdefault(name, {})
        for stat, ctr in self._m_profile.items():
            v = int(pf.get(stat) or 0)
            last = seen.get(stat, 0)
            d = v - last if v >= last else v
            seen[stat] = v
            if d > 0:
                ctr.inc(d)

    def _fold_mem(self, name, snap):
        """Harvest one heartbeat's memory-ledger digest: cache it for
        the health() rollup + the mem_headroom placement term, push
        the worst per-replica unattributed residual into the canary
        gauge, and delta-fold the engine-monotonic ledger stats into
        fleet_mem_* (the _fold_profile restart-tolerance discipline:
        a backwards value means the engine restarted — fold the new
        absolute, never a negative delta)."""
        mem = snap.get("mem")
        if not mem:
            self._mem_seen.pop(name, None)
            self._mem_digests.pop(name, None)
            return
        self._mem_digests[name] = mem
        worst = max((int(dg.get("unattributed_bytes") or 0)
                     for dg in self._mem_digests.values()), default=0)
        self._m_mem_unattr.set(worst)
        seen = self._mem_seen.setdefault(name, {})
        stats = mem.get("stats") or {}
        for stat, ctr in self._m_mem.items():
            v = int(stats.get(stat) or 0)
            last = seen.get(stat, 0)
            d = v - last if v >= last else v
            seen[stat] = v
            if d > 0:
                ctr.inc(d)

    def _fold_spec(self, name, snap):
        """Harvest one heartbeat's speculative-decoding section into
        the fleet_spec_* counters — the same restart-tolerant
        delta-fold as _fold_prefix (a backwards value means the engine
        restarted: fold the new absolute, never a negative delta)."""
        sp = snap.get("spec")
        if not sp:
            self._spec_seen.pop(name, None)
            return
        seen = self._spec_seen.setdefault(name, {})
        for stat, ctr in self._m_spec.items():
            v = int(sp.get(stat) or 0)
            last = seen.get(stat, 0)
            d = v - last if v >= last else v
            seen[stat] = v
            if d > 0:
                ctr.inc(d)

    def _fold_prefix(self, name, snap):
        """Harvest one heartbeat's prefix-cache section: refresh the
        fingerprint inventory the affinity term scores against, and
        delta-fold the engine-monotonic stats into the fleet
        counters. A value that went BACKWARDS means the engine
        restarted (stats reset with the incarnation) — fold the new
        absolute value, never a negative delta."""
        pc = snap.get("prefix_cache")
        if not pc:
            self._fpsets.pop(name, None)
            self._prefix_seen.pop(name, None)
            return
        self._fpsets[name] = (frozenset(pc.get("fingerprints") or ()),
                              int(snap.get("page_size") or 0))
        seen = self._prefix_seen.setdefault(name, {})
        for stat, ctr in self._m_prefix.items():
            v = int(pc.get(stat) or 0)
            last = seen.get(stat, 0)
            d = v - last if v >= last else v
            seen[stat] = v
            if d > 0:
                ctr.inc(d)

    def _rep_incarnation(self, name):
        """The replica's CURRENT incarnation number (bumped on every
        rejoin/respawn); None for transports that predate the
        contract. Stamped into placed/hedged journal records and
        leg_inc so the stale-incarnation guard holds across
        respawns."""
        return getattr(self.replicas.get(name), "incarnation", None)

    def _serving_candidates(self):
        out = []
        for name, rep in self.replicas.items():
            if name in self._lost or not rep.alive:
                continue
            snap = self._last_scrape.get(name)
            if snap and snap.get("state") == "serving":
                out.append((name, snap))
        return out

    def _outstanding(self):
        """Router-side per-replica unresolved assignment counts (the
        authoritative saturation signal — scrapes lag)."""
        out = {name: 0 for name in self.replicas}
        for p in self._pending.values():
            if p.done:
                continue
            for name in (p.replica, p.hedge):
                if name in out:
                    out[name] += 1
        return out

    def _affinity_fps(self, p, page_size):
        """Prefix fingerprints of a pending request's ORIGINAL prompt
        at a replica's page size, memoised on the pending (placement
        retries every control round; replicas may run different page
        sizes, so the cache is keyed by page size)."""
        if p.prefix_fps is None:
            p.prefix_fps = {}
        fps = p.prefix_fps.get(page_size)
        if fps is None:
            from ..nlp.paged_cache import prefix_fingerprints
            fps = prefix_fingerprints(p.prompt, page_size)
            p.prefix_fps[page_size] = fps
        return fps

    def _affinity_pages(self, p, name):
        """Leading prompt pages of `p` already resident in replica
        `name`'s prefix cache (per its last advertised fingerprint
        inventory) — the prefix-affinity score term."""
        fpset, ps = self._fpsets.get(name, (None, 0))
        if not fpset or not ps:
            return 0
        matched = 0
        for fp in self._affinity_fps(p, ps):
            if fp not in fpset:
                break
            matched += 1
        return matched

    def _pick_replica(self, outstanding, exclude=(), pending=None):
        """Best serving replica by scraped health: free pages up,
        queue depth / occupancy / queue-wait p99 down; capacity-capped
        by the router's own outstanding count. Deterministic tie-break
        on name. Weights come from ``placement_weights`` — a
        constructor knob so a replay what-if (or a future autotuner)
        can score alternatives without patching this method. With a
        nonzero ``prefix_affinity`` weight and a concrete `pending`,
        candidates already holding the request's prefix pages score
        higher (weight 0 — the default — skips the term entirely, so
        capacity probes and affinity-off fleets place exactly as
        before)."""
        w = self.placement_weights
        aff_w = w["prefix_affinity"]
        mem_w = w["mem_headroom"]
        best, best_key = None, None
        for name, snap in self._serving_candidates():
            if name in exclude:
                continue
            if outstanding.get(name, 0) >= self.replica_queue_limit:
                continue
            score = (w["free_pages"] * float(snap.get("free_pages", 0))
                     - w["queued"] * float(snap.get("queued", 0))
                     - w["running"] * float(snap.get("running", 0))
                     - w["queue_wait_p99_s"]
                     * float(snap.get("queue_wait_p99_s", 0.0))
                     - w["outstanding"] * outstanding.get(name, 0))
            if aff_w and pending is not None:
                score += aff_w * self._affinity_pages(pending, name)
            if mem_w:
                # forecast device headroom off the cached heartbeat
                # ledger digest (MB so the weight's scale matches the
                # page-count terms); replicas without an armed ledger
                # contribute 0 — unknown headroom is not a penalty
                dg = self._mem_digests.get(name) or {}
                hr = dg.get("headroom_bytes")
                if hr is not None:
                    score += mem_w * (float(hr) / 1e6)
            key = (score, name)
            if best_key is None or score > best_key[0] \
                    or (score == best_key[0] and name < best_key[1]):
                best, best_key = name, key
        return best

    def _unscraped(self):
        """Live replicas we have never heard a heartbeat from (fleet
        boot). Placement and shedding both wait them out: an unknown
        replica is unknown capacity, not zero capacity — and placing
        before every snapshot has landed would skew the spread."""
        return [name for name, rep in self.replicas.items()
                if name not in self._lost and rep.alive
                and name not in self._last_scrape]

    @property
    def booted(self):
        """True once every live replica's first heartbeat has landed
        (the placement boot gate is open). A load generator that
        starts its clock before this measures the fleet's boot
        transient, not its serving behaviour — tools/fleet_replay.py
        waits on this before the first scheduled arrival."""
        return not self._unscraped()

    def _expire_queued(self):
        """Requests whose deadline lapsed while still queued at the
        ROUTER resolve as expired here (placed ones expire at their
        replica's host boundaries, as before)."""
        now = time.monotonic()
        for rid in list(self._queue):
            p = self._pending[rid]
            if p.deadline is not None and now > p.deadline:
                self._queue.remove(rid)
                self._resolve(p, list(p.delivered), "expired", None)

    def _remaining_deadline_ms(self, p):
        if p.deadline is None:
            return None
        return max((p.deadline - time.monotonic()) * 1e3, 1.0)

    def _start_leg(self, p, target, hedge=False):
        """Open the replica-leg span for an assignment and return the
        context to propagate (failover continuations carry the
        prefix-dedup boundary; hedge legs are marked as such)."""
        args = {"replica": target}
        if hedge:
            args["hedge"] = True
        if p.failovers:
            args["failover_of"] = p.failovers
        if p.delivered:
            # the continuation leg: its prompt is original ‖ delivered
            # and only the remaining budget is requested — the dedup
            # boundary is THE fact a latency postmortem needs
            args.update(prefix_dedup=True,
                        prefix_tokens=len(p.delivered),
                        remaining_budget=p.max_new - len(p.delivered))
        ctx = self._tstore.start_span(p.trace, "replica_leg",
                                      proc=target, args=args)
        if ctx is not None:
            p.leg_ctxs[target] = ctx
        return ctx

    def _end_leg(self, p, name, outcome, **args):
        ctx = p.leg_ctxs.pop(name, None)
        if ctx is not None:
            self._tstore.end_span(ctx, outcome=outcome,
                                  args=args or None)

    def _submit_leg(self, p, target, prompt, max_new, hedge=False):
        """Open a replica-leg span and deliver one submit through the
        transport — trace context and REMAINING deadline ride along,
        the transport_submit child records the retry count. Returns
        (ok, leg_ctx); on transport failure the leg is closed
        ``transport_failed`` and the caller retries next round."""
        leg = self._start_leg(p, target, hedge=hedge)
        t_send = dtrace.now()
        client = self._clients[target]
        retries0 = client.stats.retries
        try:
            client.submit(p.rid, prompt, max_new, p.eos, p.priority,
                          deadline_ms=self._remaining_deadline_ms(p),
                          trace=dtrace.hop(leg), tenant=p.tenant)
        except Exception:  # noqa: BLE001 — transport gave up; retry
            self._end_leg(p, target, "transport_failed")
            return False, None
        p.leg_base[target] = len(p.delivered)
        p.leg_inc[target] = self._rep_incarnation(target)
        self._tstore.add_span(
            leg, "transport_submit", t_send, proc="router",
            args={"retries": client.stats.retries - retries0})
        return True, leg

    def _phase(self, name):
        """Serving-phase marker for the continuous profiler (no-op
        nullcontext when the router is not armed): samples taken on
        the control thread inside the block attribute to `name`."""
        if self.profiler is None:
            return contextlib.nullcontext()
        from ..observability import contprof
        return contprof.phase(name)

    def _place(self):
        # thin phase wrapper: host stack samples taken while the
        # placement loop runs attribute to the `placement` phase
        with self._phase("placement"):
            self._place_impl()

    def _place_impl(self):
        if not self._queue or self._unscraped():
            return
        outstanding = self._outstanding()
        placed = []
        # highest priority first; FIFO within a priority
        for rid in sorted(self._queue,
                          key=lambda r: (-self._pending[r].priority, r)):
            p = self._pending[rid]
            target = self._pick_replica(outstanding, pending=p)
            if target is None:
                continue
            # brownout: clamp a browned-out tenant's decode budget at
            # the placement boundary (journals BEFORE the placed
            # record, so recovery reconciles the clamped budget)
            self._maybe_brownout_clamp(p)
            prompt = p.prompt + [int(t) for t in p.delivered]
            remaining = p.max_new - len(p.delivered)
            # the placement's affinity context rides the journal: the
            # full-prefix fingerprint at the TARGET's page size, so a
            # recovered router (and any postmortem) can re-score what
            # affinity saw. None when the target never advertised a
            # prefix cache (or the prompt spans < 2 pages).
            _, ps = self._fpsets.get(target, (None, 0))
            fps = self._affinity_fps(p, ps) if ps else []
            # WAL: placement journals before the transport send (with
            # the prefix length the leg is anchored to). If the send
            # then fails (or the router dies between the two),
            # recovery re-places onto the journaled replica — the
            # idempotent-by-rid submit absorbs whichever half
            # actually happened
            self._jappend("placed", rid=rid, replica=target,
                          prefix=len(p.delivered),
                          incarnation=self._rep_incarnation(target),
                          fingerprint=fps[-1] if fps else None)
            ok, leg = self._submit_leg(p, target, prompt, remaining)
            if not ok:
                continue       # transport gave up; retry next round
            p.replica = target
            p.placed_at = time.monotonic()
            # the placement-wait hop closes where the leg opened, so
            # the root's children tile the timeline gap-free
            self._tstore.add_span(p.trace, "placement_wait",
                                  p.queue_since_pc,
                                  leg["t0"] if leg else dtrace.now(),
                                  proc="router",
                                  args={"replica": target})
            outstanding[target] = outstanding.get(target, 0) + 1
            self._routed_counter(target).inc()
            self._m_place_wait.observe(p.placed_at - p.submitted_at)
            placed.append(rid)
        for rid in placed:
            self._queue.remove(rid)

    def _shed_key(self, r):
        """Degradation order shared by every shed path: lowest
        priority goes first; within a priority band the HEAVIEST
        tenants (space-saving sketch weight) go before light ones —
        fair degradation: saturation caused by a hot tenant lands on
        that tenant first — newest first as the final tie-break."""
        p = self._pending[r]
        usage = 0 if self.tenants is None else self.tenants.usage(
            p.tenant if p.tenant is not None else "anon")
        return (p.priority, -usage, -r)

    # -- adaptive overload control (sojourn admission + brownout) ----------

    @property
    def degraded(self):
        """True while the overload controller sees a standing
        placement queue — one of the autoscaler's scale-out signals
        and the honest health()["overload"] flag."""
        return self._degraded

    @property
    def slo_alerting(self):
        """Objectives whose multi-window burn-rate pairs are firing
        (cached from the last step()'s evaluation — cheap enough for
        the autoscaler to read every poll)."""
        return sorted(n for n, r in self._slo_state.items()
                      if r.get("alert"))

    def _overload_control(self):
        """CoDel-style queue-delay admission: the static ``max_queue``
        bound sheds on LENGTH, which says nothing about how long
        clients are actually waiting. This controller watches the
        head-of-line placement sojourn instead — when it stays above
        ``overload_target_ms`` for a full ``overload_interval_s``
        while NOTHING is placeable (genuine saturation, never fleet
        boot), the router enters ``degraded``: queued requests whose
        sojourn already exceeds the target resolve ``shed`` fail-fast
        (they could not be served inside the target anyway — better
        an honest early rejection than a guaranteed SLO breach),
        worst-first in the tenant-fair shed order, while younger
        requests stay queued for the capacity the autoscaler is
        bringing up. The brownout ladder rides the same state."""
        t = self._overload_target_s
        if t is None:
            return
        now = time.monotonic()
        standing = False
        if self._queue and not self._unscraped():
            head = min(self._queue,
                       key=lambda r: (-self._pending[r].priority, r))
            sojourn = now - self._pending[head].submitted_at
            standing = sojourn > t \
                and self._pick_replica(self._outstanding()) is None
        if standing:
            if self._overload_since is None:
                self._overload_since = now
            if not self._degraded \
                    and now - self._overload_since \
                    >= self._overload_interval_s:
                self._set_degraded(True, now)
        else:
            self._overload_since = None
            if self._degraded:
                self._set_degraded(False, now)
        if self._degraded:
            victims = sorted(
                (r for r in self._queue
                 if now - self._pending[r].submitted_at > t),
                key=self._shed_key)
            shed_now = []
            for rid in victims:
                self._queue.remove(rid)
                p = self._pending[rid]
                self._m_shed.inc()
                self._m_osheds.inc()
                self._resolve(p, list(p.delivered), "shed", None)
                shed_now.append(rid)
            if shed_now:
                self._note_shed_storm(shed_now)
        self._brownout_tick(now)

    def _set_degraded(self, flag, now):
        self._degraded = bool(flag)
        self._degraded_at = now if flag else None
        self._g_degraded.set(1 if flag else 0)

    def _brownout_tick(self, now):
        """One rung per ``brownout_step_s`` while degraded (capped at
        ``brownout_levels``), one rung back down per step after
        recovery — hysteresis, never a cliff. Level L clamps the L
        heaviest tenants; the set refreshes every tick because sketch
        weights move with the traffic."""
        lvl = self._brownout_level
        if self._degraded:
            if lvl < self._brownout_levels and (
                    lvl == 0 or now - self._brownout_changed
                    >= self._brownout_step_s):
                self._set_brownout(lvl + 1, now)
        elif lvl > 0 and now - self._brownout_changed \
                >= self._brownout_step_s:
            self._set_brownout(lvl - 1, now)
        if self._brownout_level and self.tenants is not None:
            self._brownout_set = set(
                self.tenants.heaviest(self._brownout_level))

    def _set_brownout(self, level, now):
        escalating = level > self._brownout_level
        self._brownout_level = int(level)
        self._brownout_changed = now
        self._g_blevel.set(level)
        self._brownout_set = set() if level == 0 \
            or self.tenants is None \
            else set(self.tenants.heaviest(level))
        # every brownout decision is journaled; escalations also
        # flight-dump (a sustained storm is <= brownout_levels dumps)
        self._scale_log.append({"kind": "brownout",
                                "level": self._brownout_level,
                                "tenants": sorted(self._brownout_set)})
        self._jappend("brownout", level=self._brownout_level,
                      tenants=sorted(self._brownout_set))
        if escalating:
            self._flight_dump("fleet_brownout", {
                "level": self._brownout_level,
                "clamped_tenants": sorted(self._brownout_set),
                "degraded_for_s": None if self._degraded_at is None
                else round(now - self._degraded_at, 6)})

    def _maybe_brownout_clamp(self, p):
        """Placement-time budget clamp for browned-out tenants: the
        request still serves, just shorter — graceful degradation
        while capacity catches up. Journaled per rid (recovery honors
        the clamp: reconcile folds it into max_new)."""
        if not self._brownout_level or not self._brownout_set:
            return
        tname = p.tenant if p.tenant is not None else "anon"
        if tname not in self._brownout_set:
            return
        cap = len(p.delivered) + self._brownout_max_new
        if p.max_new <= cap:
            return
        p.max_new = cap
        self._bclamp_counter(tname).inc()
        self._jappend("brownout", rid=p.rid, tenant=tname,
                      level=self._brownout_level, max_new=cap)

    def _overload_health(self):
        """Overload-controller rollup for the health snapshot —
        ``degraded`` is an honest, externally visible state, not a
        silent shed counter."""
        if self._overload_target_s is None:
            return {"degraded": False, "brownout_level": 0,
                    "clamped_tenants": [], "target_s": None,
                    "degraded_for_s": None}
        now = time.monotonic()
        return {"degraded": self._degraded,
                "brownout_level": self._brownout_level,
                "clamped_tenants": sorted(self._brownout_set),
                "target_s": self._overload_target_s,
                "degraded_for_s": None if self._degraded_at is None
                else round(now - self._degraded_at, 6)}

    def _shed(self):
        if len(self._queue) <= self.max_queue:
            return
        # only shed under GENUINE saturation, never during fleet boot
        # and never while some serving replica could still take work
        # (e.g. a placement that lost its transport round retries next
        # step instead of being rejected)
        if self._unscraped() \
                or self._pick_replica(self._outstanding()) is not None:
            return
        order = sorted(self._queue, key=self._shed_key)
        shed_now = []
        while len(self._queue) > self.max_queue and order:
            rid = order.pop(0)
            self._queue.remove(rid)
            p = self._pending[rid]
            self._m_shed.inc()
            self._resolve(p, list(p.delivered), "shed", None)
            shed_now.append(rid)
        if shed_now:
            self._note_shed_storm(shed_now)

    def _note_shed_storm(self, shed_rids):
        """Shed-storm flight trigger: more than shed_storm_threshold
        sheds inside shed_storm_window_s dumps ONE flight record (with
        the last victim's trace tree), then re-arms only after the
        window drains — a sustained storm is one postmortem, not a
        dump per shed."""
        now = time.monotonic()
        cut = now - self._shed_storm_window_s
        while self._shed_times and self._shed_times[0] < cut:
            self._shed_times.popleft()
        if not self._shed_times:
            # the window drained since the last storm: re-arm BEFORE
            # counting the new batch, so a second storm whose first
            # observation already meets the threshold still dumps
            self._shed_storm_armed = True
        self._shed_times.extend(now for _ in shed_rids)
        count = len(self._shed_times)
        if count >= self._shed_storm_threshold:
            if self._shed_storm_armed:
                self._shed_storm_armed = False
                self._flight_dump("fleet_shed_storm", {
                    "shed_in_window": count,
                    "window_s": self._shed_storm_window_s,
                    "victims": list(shed_rids),
                    "victim_trace": self._victim_tree(shed_rids[-1])})
        else:
            self._shed_storm_armed = True

    def _hedge(self):
        if not self.hedge_after_ms:
            return
        now = time.monotonic()
        outstanding = self._outstanding()
        for rid, p in self._pending.items():
            if p.done or p.replica is None or p.hedge is not None \
                    or p.delivered or p.placed_at is None:
                continue
            if (now - p.placed_at) * 1e3 < float(self.hedge_after_ms):
                continue
            target = self._pick_replica(outstanding,
                                        exclude={p.replica},
                                        pending=p)
            if target is None:
                continue
            ok, _leg = self._submit_leg(p, target, p.prompt,
                                        p.max_new, hedge=True)
            if not ok:
                continue
            p.hedge = target
            p.hedged = True
            # journaled so a successor can find (and cancel) a hedge
            # leg orphaned by a router crash instead of letting it
            # decode to a result nobody will read
            self._jappend("hedged", rid=rid, replica=target,
                          incarnation=self._rep_incarnation(target))
            outstanding[target] = outstanding.get(target, 0) + 1
            self._m_hedges.inc()

    def _recover_lost(self):
        now = time.monotonic()
        for name, rep in self.replicas.items():
            if name in self._lost:
                continue
            reason = None
            if not rep.alive and rep.state == "dead":
                reason = "crash"
            elif rep.alive and rep.state in ("serving", "draining"):
                snap = self._last_scrape.get(name)
                if snap and now - snap["ts"] > self.wedge_timeout_s:
                    reason = "wedge"
            elif not rep.alive and rep.state == "drained":
                # parked cleanly; recover any straggler assignments
                # (a submit that raced the drain into a dead inbox)
                self._recover_assignments(name, "drain", rep)
                continue
            if reason is None:
                continue
            if rep.alive:
                rep.kill()  # unstick the wedge; thread exits
            self._lost.add(name)
            self._recover_assignments(name, reason, rep)

    def _recover_assignments(self, name, reason, rep):
        """Fail over every unresolved request assigned to `name`:
        harvest finished results first, recover partial tokens from
        the carcass, then continuation-resubmit (completed prefix
        deduped) or finish straight from the prefix."""
        try:
            harvested = rep.pop_results()
        except Exception:  # noqa: BLE001 — best-effort harvest
            harvested = []
        self._handle_batch(harvested, rep.ack)
        try:
            carcass = {e["rid"]: e for e in rep.export_inflight()}
        except Exception:  # noqa: BLE001 — carcass unreadable: resubmit
            carcass = {}   # from scratch (still correct, just slower)
        victims = []
        for rid, p in list(self._pending.items()):
            if p.done:
                continue
            hit = False
            if p.replica == name:
                p.replica = None
                hit = True
            if p.hedge == name:
                p.hedge = None
                hit = True
            if not hit:
                continue
            p.failovers += 1
            self._failover_counter(name, reason).inc()
            self._jappend("failover", rid=rid, replica=name,
                          reason=reason,
                          incarnation=p.leg_inc.get(name))
            ent = carcass.get(rid)
            if ent:
                # carcass tokens are relative to the prefix THIS leg
                # was placed with (a continuation's partials must
                # extend the old prefix, never replace it)
                base = p.leg_base.get(name, len(p.delivered))
                cand = p.delivered[:base] \
                    + [int(t) for t in ent.get("tokens") or []]
                if len(cand) > len(p.delivered):
                    p.delivered = cand
                    self._jappend("delivered", rid=rid,
                                  tokens=p.delivered)
            # the lost leg stays in the tree: the continuation leg
            # that follows is causally linked to it through the shared
            # root, and the harvested prefix length is right here
            self._end_leg(p, name, "failover_source", reason=reason,
                          recovered_tokens=len(p.delivered))
            victims.append(rid)
            if p.replica is not None or p.hedge is not None:
                continue  # the other leg is still running it
            if rid in self._queue:
                continue
            # router-resident again as of the recovery moment (see
            # the bounce path: reset BEFORE the prefix-finish attempt)
            p.queue_since_pc = dtrace.now()
            if not self._finish_from_prefix(p):
                self._queue.append(rid)
        if victims:
            # "failover_reason", not "reason" — flightrec.dump owns
            # the top-level "reason" field (the dump's trigger tag)
            self._flight_dump("fleet_failover", {
                "replica": name, "failover_reason": reason,
                "victims": victims,
                "victim_trace": self._victim_tree(victims[0])})

    def _victim_tree(self, rid):
        p = self._pending.get(rid)
        if p is None or p.trace is None:
            return None
        return self._tstore.tree(p.trace["trace_id"])

    def _flight_dump(self, tag, extra):
        """Flight-recorder trigger with the fleet registry snapshot
        and the fleet health rollup attached (never raises — a
        postmortem write must not take the router down)."""
        try:
            from ..observability import contprof, flightrec, memledger
            flightrec.note(tag, **{k: v for k, v in extra.items()
                                   if not isinstance(v, dict)})
            flightrec.dump(tag, extra=dict(
                extra, fleet_registry=self._registry_snapshot(),
                fleet_health=self.health(),
                # what was the PROCESS actually doing when the
                # anomaly tripped — the last ~minute of host stacks
                # (None when no profiler is armed in-process)
                profile=contprof.current_profile(),
                # and where device memory stood: the active ledger's
                # segment tree + headroom (None when none is armed)
                memory=memledger.current_memory()))
        except Exception:  # noqa: BLE001
            pass

    # -- write-ahead journal + crash recovery -------------------------------

    def _jappend(self, kind, **fields):
        """Append one lifecycle record; a transient failure parks the
        record in the retry backlog (flushed at every step) and
        returns False. While ANY record is parked, later records
        queue behind it — reconcile() folds per-rid records in
        journal order, so a stale `failover` flushed after a newer
        `placed` would otherwise erase the live placement at
        recovery. (`accepted` bypasses this: it is always its rid's
        FIRST record, so submit() appends directly.) JournalCrash
        propagates — the router is dead at that write, which is the
        point of the seam."""
        # profiler phase: journal fsync stalls show up as `journal`
        # samples, not smeared into whatever phase enclosed the append
        with self._phase("journal"):
            return self._jappend_impl(kind, **fields)

    def _jappend_impl(self, kind, **fields):
        if self._journal is None:
            return True
        if self._jbacklog:
            self._jbacklog.append((kind, fields))
            self._flush_jbacklog()
            return not self._jbacklog
        try:
            self._journal.append(kind, **fields)
            return True
        except JournalCrash:
            raise
        except JournalError:
            self._jbacklog.append((kind, fields))
            return False

    def _flush_jbacklog(self):
        """Retry parked appends; a `resolved` record going durable
        unblocks the replica-side ack for its result."""
        if self._journal is None or not self._jbacklog:
            return
        with self._phase("journal"):
            self._flush_jbacklog_impl()

    def _flush_jbacklog_impl(self):
        backlog, self._jbacklog = self._jbacklog, []
        for i, (kind, fields) in enumerate(backlog):
            try:
                self._journal.append(kind, **fields)
            except JournalCrash:
                self._jbacklog = backlog[i:] + self._jbacklog
                raise
            except JournalError:
                self._jbacklog.append((kind, fields))
                continue
            if kind == "resolved":
                self._junacked_rids.discard(fields["result"]["id"])

    def _deadline_epoch(self, p):
        if p.deadline is None:
            return None
        return round(time.time() + (p.deadline - time.monotonic()), 6)

    def _snapshot_records(self):
        """The compaction payload segment rotation writes at the head
        of a fresh segment: every unresolved request (with its
        delivered prefix and last placement) + every resolved-but-
        unpopped result. Retired rids are dropped — that IS the
        compaction."""
        now_w, now_m = time.time(), time.monotonic()
        recs = []
        for rid, p in sorted(self._pending.items()):
            if p.done:
                continue
            recs.append({
                "kind": "snap_req", "rid": rid, "prompt": p.prompt,
                "max_new": p.max_new, "eos": p.eos,
                "priority": p.priority, "tenant": p.tenant,
                "deadline_epoch": self._deadline_epoch(p),
                "submitted_epoch": round(
                    now_w - (now_m - p.submitted_at), 6),
                "delivered": [int(t) for t in p.delivered],
                "replica": p.replica,
                "placed_prefix": None if p.replica is None
                else p.leg_base.get(p.replica, len(p.delivered)),
                "placed_incarnation": None if p.replica is None
                else p.leg_inc.get(p.replica),
                "hedge": p.hedge, "failovers": p.failovers})
        for rid in sorted(self._done):
            recs.append({"kind": "snap_done",
                         "result": dict(self._done[rid])})
        # the scale/brownout story rides compaction (bounded): a
        # successor can always answer "why is the fleet this size"
        recs.extend(dict(r) for r in self._scale_log)
        return recs

    @classmethod
    def recover(cls, journal_dir, replicas, *, rejoin_parked=True,
                **router_kw):
        """Bring up a successor router from a dead one's journal +
        its still-live replicas. Returns the recovered FleetRouter
        (journaling into the same directory, compacted).

        The recovery algorithm (docs/robustness.md "Router durability
        & recovery"):

        1. **Replay** the newest finalized journal segment, dropping
           at most a torn tail, and **reconcile** the records into
           per-rid terminal state (journal.reconcile).
        2. **Re-adopt** the replicas: parked carcasses (drained on
           preemption, dead after a crash) are rejoined on the SAME
           engine — zero recompiles — and every replica's retained
           result plane is re-polled (results the dead router fetched
           but never durably processed come back; the ack happened
           only after the `resolved` record was journaled, so nothing
           is both acked and unjournaled).
        3. **Restore** resolved-but-unretired results straight into
           the done buffer (delivered exactly once across the crash)
           and reinstate every unresolved request with its journaled
           delivered prefix.
        4. **Reconcile placement**: an unresolved rid journaled onto
           a live serving replica is continuation-resubmitted THERE —
           idempotent by rid, so "still running" and "the placed
           record outran the transport" both land right; one journaled
           onto a dead replica keeps the assignment so the normal
           failover path harvests the carcass; the rest re-queue. The
           continuation prompt is ``original ‖ delivered`` with the
           remaining budget — token-exact vs an uninterrupted router,
           zero new compiles on the re-adopted engines.
        5. **Compact** the journal (rotation with a snapshot head) and
           dump a ``fleet_router_recovery`` flight record.

        rejoin_parked: restart drained/crashed replica workers during
        adoption (same engine). Pass False to adopt only what is
        already alive."""
        records, stats = replay(journal_dir)
        state = reconcile(records)
        router = cls(replicas, journal_dir=journal_dir, **router_kw)
        router._adopt(state, stats, rejoin_parked=rejoin_parked)
        return router

    def _adopt(self, state, stats, rejoin_parked=True):
        j = self._journal
        if j is not None:
            j._inc("replay_records", stats["replay_records"])
            j._inc("torn_tail_drops", stats["torn_tail_drops"])
        self._next_rid = max(self._next_rid, int(state["next_rid"]))
        # the dead incarnation's scale/brownout decisions: surfaced
        # to the successor's operator/autoscaler and re-carried
        # through this recovery's own compaction below
        self.recovered_autoscale = [dict(r) for r in
                                    state.get("autoscale") or []]
        self._scale_log.extend(self.recovered_autoscale)
        now_m, now_w = time.monotonic(), time.time()
        adopted = {}
        for name, rep in self.replicas.items():
            if rejoin_parked and not rep.alive \
                    and rep.engine.state != "closed":
                try:
                    rep.rejoin()
                except Exception:  # noqa: BLE001 — adopt what we can;
                    pass           # the failover path owns the rest
            adopted[name] = {"alive": rep.alive, "state": rep.state}
        restored_done, reinstated = [], []
        distrusted = {}   # rid -> journaled replica to pre-cancel
        for rid, e in sorted(state["requests"].items()):
            if e["resolved"] is not None:
                # resolved pre-crash, never popped: re-deliver exactly
                # once (metrics were counted by the dead incarnation —
                # don't double-count)
                self._done[rid] = dict(e["resolved"])
                restored_done.append(rid)
                continue
            if e["prompt"] is None:
                continue   # orphan records (torn `accepted`): nothing
                #            to rebuild a resubmission from
            deadline = None
            if e["deadline_epoch"] is not None:
                deadline = now_m + (float(e["deadline_epoch"]) - now_w)
            p = _Pending(rid, e["prompt"], e["max_new"], e["eos"],
                         e["priority"], deadline=deadline,
                         tenant=e.get("tenant"))
            if e["submitted_epoch"] is not None:
                p.submitted_at = now_m - max(
                    now_w - float(e["submitted_epoch"]), 0.0)
            p.delivered = [int(t) for t in e["delivered"]]
            p.failovers = int(e["failovers"])
            name = e["replica"] if e["replica"] in self.replicas \
                else None
            pp = e.get("placed_prefix")
            if name is not None and pp is not None \
                    and pp != len(p.delivered):
                # distrusted placement: the journal's delivered
                # watermark does not match the prefix the leg was
                # placed with (a `delivered` record lost to a disk
                # fault, or a bounce whose clearing never journals).
                # Any result from that leg would stitch against the
                # wrong anchor — cancel it best-effort and recompute
                # from the prefix we CAN prove; the stale-leg guard
                # in _handle drops whatever it still emits
                distrusted[rid] = name
            elif name is not None:
                p.replica = name
                p.leg_base[name] = len(p.delivered) if pp is None \
                    else int(pp)
                # seed the incarnation the leg was journaled with, so
                # the harvest below accepts that incarnation's retained
                # results and rejects any other incarnation's flushes
                if e.get("placed_incarnation") is not None:
                    p.leg_inc[name] = int(e["placed_incarnation"])
            p.trace = self._tstore.new_trace(
                name="request", proc="router", rid=rid,
                args={"prompt_len": len(p.prompt),
                      "max_new": p.max_new, "priority": p.priority,
                      "recovered": True, "failovers": p.failovers})
            if p.trace is not None:
                self._trace_ids.append(p.trace["trace_id"])
            # a journaled cancel intent survives the crash: seed the
            # in-memory set BEFORE the harvest so the replica's
            # 'cancelled' result (if the pre-crash cancel reached it)
            # resolves as the solicited cancel it is, not as a bounce
            # that would requeue the request
            if rid in state["cancelled"]:
                self._cancel_requested.add(rid)
            self._pending[rid] = p
            reinstated.append(rid)
        if self._m_recovered is not None and reinstated:
            self._m_recovered.inc(len(reinstated))
        # harvest: first heartbeats + the retained result plane. A
        # result handled here resolves/bounces through the normal
        # paths (journaling as it goes); one for a restored-done or
        # retired rid finds no pending entry and dedups
        self._scrape_all()
        self._collect()
        for rid, name in distrusted.items():
            p = self._pending.get(rid)
            if p is None or p.done:
                continue
            try:
                self._clients[name].cancel(rid)
            except Exception:  # noqa: BLE001 — its results are
                pass           # dropped by the stale-leg guard anyway
        re_placed, requeued = [], []
        for rid in reinstated:
            p = self._pending.get(rid)
            if p is None or p.done:
                continue
            # a hedge leg is never re-adopted (the primary is), but a
            # crash orphaned it mid-decode — cancel it so it stops
            # burning a slot on a result the stale-leg guard would
            # drop anyway
            hedge_name = state["requests"][rid].get("hedge")
            if hedge_name in self._clients:
                try:
                    self._clients[hedge_name].cancel(rid)
                except Exception:  # noqa: BLE001 — best-effort
                    pass
            if rid in state["cancelled"]:
                # the client cancelled this pre-crash: resolve it
                # cancelled with what was delivered instead of
                # spending the remaining budget on an unwanted result
                if p.replica in self._clients:
                    try:
                        self._clients[p.replica].cancel(rid)
                    except Exception:  # noqa: BLE001 — best-effort
                        pass
                if rid in self._queue:
                    self._queue.remove(rid)
                p.replica = None
                self._resolve(p, list(p.delivered), "cancelled", None)
                continue
            if rid in self._queue:
                continue
            if self._finish_from_prefix(p):
                continue
            name = p.replica
            rep = self.replicas.get(name) if name is not None else None
            pi = p.leg_inc.get(name) if name is not None else None
            cur = getattr(rep, "incarnation", None)
            if rep is not None and pi is not None \
                    and cur is not None and pi != cur:
                # the journaled leg's incarnation is gone — the
                # replica respawned/rejoined between the placement and
                # this recovery. Same name, FRESH engine: nothing
                # there is running this rid (its carcass died with the
                # old incarnation), so neither "still running" nor
                # "harvest the carcass" applies. Re-place it from the
                # provable delivered prefix like any unplaced request;
                # the stale-incarnation guard drops whatever the old
                # incarnation may still flush
                p.replica = None
                p.leg_inc.pop(name, None)
                p.queue_since_pc = dtrace.now()
                self._queue.append(rid)
                requeued.append(rid)
                continue
            if rep is not None and not rep.alive:
                continue  # carcass: step()'s failover path harvests it
            if rep is not None and rep.alive and rep.state == "serving":
                prompt = p.prompt + [int(t) for t in p.delivered]
                remaining = p.max_new - len(p.delivered)
                self._jappend("placed", rid=rid, replica=name,
                              prefix=len(p.delivered),
                              incarnation=cur)
                ok, _leg = self._submit_leg(p, name, prompt, remaining)
                if ok:
                    p.placed_at = time.monotonic()
                    self._routed_counter(name).inc()
                    re_placed.append(rid)
                    continue
            p.replica = None
            p.queue_since_pc = dtrace.now()
            self._queue.append(rid)
            requeued.append(rid)
        if j is not None:
            j.rotate(self._snapshot_records(), next_rid=self._next_rid)
        self._flight_dump("fleet_router_recovery", {
            "journal_dir": None if j is None else j.dir,
            "replay": dict(stats),
            "restored_done": restored_done,
            "reinstated": reinstated, "re_placed": re_placed,
            "requeued": requeued, "retired_rids": len(state["retired"]),
            "sealed": bool(state["sealed"]),
            "preempted": bool(state["preempted"]),
            "autoscale_records": len(self.recovered_autoscale),
            "replicas_adopted": adopted})
