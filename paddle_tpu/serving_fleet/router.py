"""FleetRouter — health-routed load balancing over N serving replicas.

The fleet layer the ROADMAP's "millions of users" north star needs: a
single admission point over many ServingEngine replicas that keeps
every client request alive through replica crashes, wedges, drains
and saturation — with the same zero-recompile discipline the engines
themselves keep (every mechanism below is host-side bookkeeping; no
replica ever compiles anything because of the router).

Mechanisms (docs/robustness.md "Fleet serving" has the contracts):

- **Placement by scrape.** Requests enter a global queue and are
  placed by scoring each replica's last published health/metrics
  snapshot (free KV pages, queued/running depth, queue-wait p99,
  lifecycle state) — the same facts the round-10 ``/metrics`` +
  ``/healthz`` endpoints expose, so a real multi-process deployment
  scrapes HTTP instead of a lock. Stale scrapes degrade gracefully
  (route on the previous snapshot; count ``fleet_scrape_errors``).
- **Failover with prefix dedup.** A dead (``replica_crash``) or
  silent (``replica_wedge``, heartbeat older than
  ``wedge_timeout_s``) replica's unfinished requests are recovered
  from its carcass (``export_inflight``) and continuation-resubmitted
  elsewhere: the new prompt is ``original ‖ tokens_already_decoded``
  and only the REMAINING budget is requested, so the client's final
  stream is the completed prefix + the continuation — token-exact
  under greedy decoding, never a duplicated token.
- **Hedging.** With ``hedge_after_ms`` set, a request stuck past the
  threshold on its primary gets a duplicate on the next-best replica;
  the first finisher wins and the loser is cancelled (first-winner
  dedup — the client sees exactly one result).
- **Graceful drain / rejoin.** ``drain(name)`` flows through the
  replica into ``ServingEngine.drain()`` (the resilience/preemption
  seam: a process-level SIGTERM drains every replica the same way):
  in-flight requests finish token-exactly, queued ones bounce back
  and re-place on healthy replicas. ``rejoin(name)`` restarts the
  worker on the SAME engine — compiled programs carry over, so a full
  drain/rejoin cycle costs zero recompiles.
- **Load shedding by priority.** When every serving replica is at its
  outstanding-work limit and the global queue exceeds ``max_queue``,
  the lowest-priority (newest-first within a priority) queued
  requests resolve with ``status="shed"`` — predictable degradation
  instead of unbounded queueing.

The router publishes its own MetricsRegistry (catalogue in
docs/observability.md) and serves it live via ``serve_metrics()`` —
the router is itself a scrape target. Control flow is single-threaded
by design: one thread drives ``step()``/``run_to_completion()``;
replica workers run on their own daemon threads behind the transport
seam.
"""
from __future__ import annotations

import time

from ..observability.metrics import MetricsRegistry
from .client import ReplicaClient

__all__ = ["FleetRouter"]


class _Pending:
    """Router-side state of one fleet request."""

    __slots__ = ("rid", "prompt", "max_new", "eos", "priority",
                 "submitted_at", "placed_at", "replica", "hedge",
                 "delivered", "failovers", "hedged", "done")

    def __init__(self, rid, prompt, max_new, eos, priority):
        self.rid = rid
        self.prompt = [int(t) for t in prompt]
        self.max_new = int(max_new)
        self.eos = eos
        self.priority = int(priority)
        self.submitted_at = time.monotonic()
        self.placed_at = None
        self.replica = None     # primary assignment (replica name)
        self.hedge = None       # hedge assignment (replica name)
        self.delivered = []     # tokens recovered from a lost replica
        self.failovers = 0
        self.hedged = False
        self.done = False


class FleetRouter:
    """Fault-tolerant request router over serving replicas.

    replicas: iterable of InprocReplica (names must be unique).
    registry: MetricsRegistry for the fleet_* series (default: a
        private one, mirroring ServingEngine's registry semantics).
    max_queue: global placement-queue bound; beyond it the lowest-
        priority queued requests are shed.
    replica_queue_limit: max outstanding (router-placed, unfinished)
        requests per replica — the saturation definition.
    hedge_after_ms: duplicate a request stuck this long on its
        primary onto a second replica (None = hedging off).
    wedge_timeout_s: a live replica whose heartbeat is older than
        this is declared wedged, killed, and failed over. The worker
        can only heartbeat BETWEEN engine rounds, so this must exceed
        the worst single dispatch/compile the replica can legally pay
        (an unwarmed prefill bucket on real hardware is seconds) —
        too tight a timeout turns a slow compile into a fleet-wide
        kill cascade. Default 10s; chaos tests pin it low only
        because their buckets are pre-warmed.
    transport_retries / retry_jitter: ReplicaClient backoff knobs;
        each client gets a distinct jitter seed so fleet-wide retries
        de-synchronize (resilience.retry.backoff_schedule).
    """

    def __init__(self, replicas, *, registry=None, max_queue=64,
                 replica_queue_limit=4, hedge_after_ms=None,
                 wedge_timeout_s=10.0, transport_retries=3,
                 retry_jitter=0.5):
        self.replicas = {}
        self._clients = {}
        for i, rep in enumerate(replicas):
            if rep.name in self.replicas:
                raise ValueError(f"duplicate replica name {rep.name!r}")
            self.replicas[rep.name] = rep
            self._clients[rep.name] = ReplicaClient(
                rep, retries=transport_retries, jitter=retry_jitter,
                jitter_seed=i)
        if not self.replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.max_queue = int(max_queue)
        self.replica_queue_limit = int(replica_queue_limit)
        self.hedge_after_ms = hedge_after_ms
        self.wedge_timeout_s = float(wedge_timeout_s)

        self._pending = {}          # rid -> _Pending (retired when the
        #                             result is popped via results())
        self._queue = []            # rids awaiting placement
        self._done = {}             # rid -> result dict (until popped)
        self._cancel_requested = set()
        self._lost = set()          # failed-over, awaiting rejoin
        self._last_scrape = {}      # name -> last good snapshot
        self._next_rid = 0
        self._exporter = None
        self._closed = False

        self.registry = registry if registry is not None \
            else MetricsRegistry()
        reg = self.registry
        self._m_req = {}
        self._m_routed = {}
        self._m_failover = {}
        self._m_requeued = reg.counter(
            "fleet_requeued_total",
            help="requests re-placed after a drain bounce")
        self._m_hedges = reg.counter(
            "fleet_hedges_total",
            help="duplicate submissions issued by tail-latency hedging")
        self._m_hedge_wins = {}
        self._m_shed = reg.counter(
            "fleet_shed_total",
            help="requests rejected by priority load shedding")
        self._m_scrape_errors = reg.counter(
            "fleet_scrape_errors_total",
            help="replica health scrapes that failed (stale routing)")
        self._m_place_wait = reg.histogram(
            "fleet_placement_wait_seconds",
            help="submit -> placement-decision wait (the router-level "
                 "queueing leg)")
        self._g_queue = reg.gauge(
            "fleet_queue_depth", help="requests awaiting placement")
        self._g_pending = reg.gauge(
            "fleet_pending", help="accepted, unresolved requests")
        self._g_serving = reg.gauge(
            "fleet_replicas_serving",
            help="replicas currently placeable")

    # -- metric series (lazy per label) -----------------------------------

    def _labeled(self, cache, name, help, **labels):
        key = tuple(sorted(labels.items()))
        c = cache.get(key)
        if c is None:
            c = self.registry.counter(name, help=help, labels=labels)
            cache[key] = c
        return c

    def _req_counter(self, status):
        return self._labeled(
            self._m_req, "fleet_requests_total",
            "resolved fleet requests by terminal status", status=status)

    def _routed_counter(self, replica):
        return self._labeled(
            self._m_routed, "fleet_routed_total",
            "requests placed, per replica", replica=replica)

    def _failover_counter(self, replica, reason):
        return self._labeled(
            self._m_failover, "fleet_failovers_total",
            "in-flight requests recovered off a lost replica",
            replica=replica, reason=reason)

    def _hedge_win_counter(self, by):
        return self._labeled(
            self._m_hedge_wins, "fleet_hedge_wins_total",
            "hedged requests by which leg finished first", by=by)

    # -- public API --------------------------------------------------------

    def submit(self, prompt, max_new_tokens=16, eos_token_id=None,
               priority=0):
        """Accept one request into the fleet; returns its fleet rid.
        Placement happens at the next step()."""
        if self._closed:
            raise RuntimeError("FleetRouter is closed")
        rid = self._next_rid
        self._next_rid += 1
        self._pending[rid] = _Pending(rid, prompt, max_new_tokens,
                                      eos_token_id, priority)
        self._queue.append(rid)
        return rid

    def step(self):
        """One control round: harvest results, scrape health, fail
        over lost replicas, place/shed/hedge. Returns the results
        resolved this round."""
        if self._closed:
            raise RuntimeError("FleetRouter is closed")
        before = set(self._done)
        self._collect()
        self._scrape_all()
        self._recover_lost()
        self._place()
        self._shed()
        self._hedge()
        self._g_queue.set(len(self._queue))
        self._g_pending.set(
            sum(1 for p in self._pending.values() if not p.done))
        self._g_serving.set(len(self._serving_candidates()))
        return [self._done[r] for r in self._done if r not in before]

    def run_to_completion(self, timeout_s=120.0, poll_s=0.002):
        """Drive step() until every accepted request resolves; returns
        all results in rid order (cleared from the done buffer)."""
        t_end = time.monotonic() + float(timeout_s)
        while any(not p.done for p in self._pending.values()):
            self.step()
            if not any(not p.done for p in self._pending.values()):
                break
            if time.monotonic() > t_end:
                stuck = sorted(r for r, p in self._pending.items()
                               if not p.done)
                raise RuntimeError(
                    f"fleet did not drain within {timeout_s}s; "
                    f"unresolved rids: {stuck[:10]}")
            time.sleep(poll_s)
        return self.results()

    def results(self):
        """Pop resolved results, rid order. Popping also retires the
        router-side request state: a long-lived router stays bounded
        by its in-flight window, not its lifetime request count (rids
        never repeat, so a stray late result for a retired rid simply
        finds no pending entry and is dropped — the same dedup as
        before, without the unbounded table)."""
        out = [self._done[r] for r in sorted(self._done)]
        for r in self._done:
            self._pending.pop(r, None)
        self._done = {}
        return out

    def generate(self, prompts, max_new_tokens=16, eos_token_id=None):
        """Convenience batch API (mirrors ServingEngine.generate):
        submit all, drain the fleet, return token lists in submission
        order."""
        ids = [self.submit(p, max_new_tokens, eos_token_id)
               for p in prompts]
        res = {r["id"]: r for r in self.run_to_completion()}
        return [res[i]["tokens"] for i in ids]

    def drain(self, name):
        """Gracefully drain one replica (same seam a preemption notice
        uses): stops admitting, finishes in-flight, bounces queued
        work back for re-placement."""
        self.replicas[name].drain()

    def rejoin(self, name):
        """Bring a drained/failed replica back into rotation (same
        engine — zero recompiles)."""
        self.replicas[name].rejoin()
        self._lost.discard(name)
        self._last_scrape.pop(name, None)

    def cancel(self, rid):
        """Cancel a fleet request wherever it currently lives."""
        p = self._pending.get(rid)
        if p is None or p.done:
            return False
        self._cancel_requested.add(rid)
        if rid in self._queue:
            self._queue.remove(rid)
            self._resolve(p, list(p.delivered), "cancelled", None)
            return True
        for name in (p.replica, p.hedge):
            if name is not None and name in self._clients:
                try:
                    self._clients[name].cancel(rid)
                except Exception:  # noqa: BLE001 — transport gave up
                    pass
        return True

    def health(self):
        """Fleet-wide snapshot: per-replica state + last scrape age,
        queue/pending depth, lost set. What an operator (or an outer
        LB) pages on."""
        now = time.monotonic()
        reps = {}
        for name, rep in self.replicas.items():
            snap = self._last_scrape.get(name)
            reps[name] = {
                "alive": rep.alive, "state": rep.state,
                "lost": name in self._lost,
                "scrape_age_s": (None if snap is None
                                 else round(now - snap["ts"], 6)),
                "queued": snap.get("queued") if snap else None,
                "running": snap.get("running") if snap else None,
                "free_pages": snap.get("free_pages") if snap else None,
                "error": rep.error}
        # list() snapshots: health() also runs on metrics-exporter
        # HTTP threads, and the control thread may be mid-submit
        return {"replicas": reps,
                "queue_depth": len(self._queue),
                "pending": sum(1 for p in list(self._pending.values())
                               if not p.done),
                "lost": sorted(self._lost),
                "compile_report": self.compile_report()}

    def compile_report(self):
        """Per-replica compile counts + fleet-wide unexpected-retrace
        total — the zero-recompile assertion's fleet form (must stay
        frozen through crash/drain/rejoin waves)."""
        reps = {}
        unexpected = 0
        for name, rep in self.replicas.items():
            reps[name] = rep.engine.compile_counts()
            unexpected += rep.engine.tracer.unexpected_retraces()
        return {"replicas": reps, "unexpected_retraces": unexpected}

    def serve_metrics(self, port=0, host="127.0.0.1"):
        """Attach a live HTTP exporter to the ROUTER: /metrics is the
        fleet registry, /healthz is health(). The router is a scrape
        target just like its replicas."""
        from ..observability.exporter import MetricsExporter
        if self._exporter is not None:
            self._exporter.close()
        self._exporter = MetricsExporter(registry=self.registry,
                                         port=port, host=host,
                                         health_fn=self.health)
        return self._exporter

    def close(self):
        """Stop every replica worker and the exporter. Engines are
        NOT closed (the router does not own them); idempotent."""
        if self._closed:
            return
        self._closed = True
        for rep in self.replicas.values():
            rep.kill()
        if self._exporter is not None:
            self._exporter.close()
            self._exporter = None

    # -- control-plane internals --------------------------------------------

    def _collect(self):
        for name in self.replicas:
            try:
                batch = self._clients[name].poll()
            except Exception:  # noqa: BLE001 — transport gave up; retry
                continue       # next round (results stay queued)
            for res in batch:
                self._handle(res)

    def _handle(self, res):
        rid = res["id"]
        p = self._pending.get(rid)
        if p is None or p.done:
            return  # stray: hedge loser, post-rejoin flush — dedup
        src = res.get("replica")
        status = res["status"]
        unsolicited_cancel = (status == "cancelled"
                              and rid not in self._cancel_requested)
        if status == "bounced" or unsolicited_cancel:
            if src not in (p.replica, p.hedge):
                # stale leg: a rejoined replica flushing its pre-crash
                # slot, or a late bounce from a replica this rid was
                # already failed over FROM. Its tokens were either
                # harvested from the carcass at failover time or
                # deliberately restarted from scratch — folding them
                # in here could duplicate the prefix of a from-scratch
                # resubmit already running elsewhere
                return
            # drain bounce: the replica gave the request back — keep
            # the longest token prefix seen and re-place
            toks = res.get("tokens") or []
            if len(toks) > len(p.delivered):
                p.delivered = list(toks)
            if src == p.replica:
                p.replica = None
            if src == p.hedge:
                p.hedge = None
            if p.replica is None and p.hedge is None \
                    and rid not in self._queue:
                self._m_requeued.inc()
                if not self._finish_from_prefix(p):
                    self._queue.append(rid)
            return
        if status == "cancelled":
            # the cancel WE asked for. Hedge losers never reach this
            # (their rid is already done → dedup above); what remains
            # is a client-initiated cancel of a running request, which
            # resolves with its partial tokens
            self._cancel_requested.discard(rid)
            self._resolve(p, p.delivered + list(res.get("tokens") or []),
                          "cancelled", src)
            return
        # terminal: ok | expired — first finisher wins
        tokens = p.delivered + list(res.get("tokens") or [])
        if p.hedged and p.replica is not None and p.hedge is not None:
            loser = p.hedge if src == p.replica else p.replica
            by = "primary" if src == p.replica else "hedge"
            self._hedge_win_counter(by).inc()
            self._cancel_requested.add(rid)
            try:
                self._clients[loser].cancel(rid)
            except Exception:  # noqa: BLE001 — loser may already be gone
                pass
        self._resolve(p, tokens, status, src)

    def _finish_from_prefix(self, p):
        """A recovered prefix may already satisfy the request (eos
        seen, or budget exhausted) — resolve without resubmitting.
        Returns True when resolved."""
        d = p.delivered
        if p.eos is not None and p.eos in d:
            self._resolve(p, d[:d.index(p.eos) + 1], "ok", None)
            return True
        if len(d) >= p.max_new:
            self._resolve(p, d[:p.max_new], "ok", None)
            return True
        return False

    def _resolve(self, p, tokens, status, replica):
        p.done = True
        self._cancel_requested.discard(p.rid)
        self._req_counter(status).inc()
        self._done[p.rid] = {
            "id": p.rid, "tokens": [int(t) for t in tokens],
            "status": status, "replica": replica,
            "failovers": p.failovers, "hedged": p.hedged,
            "age_s": round(time.monotonic() - p.submitted_at, 6)}

    def _scrape_all(self):
        for name, rep in self.replicas.items():
            if name in self._lost:
                continue
            try:
                snap = rep.scrape()
            except Exception:  # noqa: BLE001 — scrape timeout: route stale
                self._m_scrape_errors.inc()
                continue
            if snap:
                self._last_scrape[name] = snap

    def _serving_candidates(self):
        out = []
        for name, rep in self.replicas.items():
            if name in self._lost or not rep.alive:
                continue
            snap = self._last_scrape.get(name)
            if snap and snap.get("state") == "serving":
                out.append((name, snap))
        return out

    def _outstanding(self):
        """Router-side per-replica unresolved assignment counts (the
        authoritative saturation signal — scrapes lag)."""
        out = {name: 0 for name in self.replicas}
        for p in self._pending.values():
            if p.done:
                continue
            for name in (p.replica, p.hedge):
                if name in out:
                    out[name] += 1
        return out

    def _pick_replica(self, outstanding, exclude=()):
        """Best serving replica by scraped health: free pages up,
        queue depth / occupancy / queue-wait p99 down; capacity-capped
        by the router's own outstanding count. Deterministic tie-break
        on name."""
        best, best_key = None, None
        for name, snap in self._serving_candidates():
            if name in exclude:
                continue
            if outstanding.get(name, 0) >= self.replica_queue_limit:
                continue
            score = (float(snap.get("free_pages", 0))
                     - 8.0 * float(snap.get("queued", 0))
                     - 2.0 * float(snap.get("running", 0))
                     - 50.0 * float(snap.get("queue_wait_p99_s", 0.0))
                     - 4.0 * outstanding.get(name, 0))
            key = (score, name)
            if best_key is None or score > best_key[0] \
                    or (score == best_key[0] and name < best_key[1]):
                best, best_key = name, key
        return best

    def _unscraped(self):
        """Live replicas we have never heard a heartbeat from (fleet
        boot). Placement and shedding both wait them out: an unknown
        replica is unknown capacity, not zero capacity — and placing
        before every snapshot has landed would skew the spread."""
        return [name for name, rep in self.replicas.items()
                if name not in self._lost and rep.alive
                and name not in self._last_scrape]

    def _place(self):
        if not self._queue or self._unscraped():
            return
        outstanding = self._outstanding()
        placed = []
        # highest priority first; FIFO within a priority
        for rid in sorted(self._queue,
                          key=lambda r: (-self._pending[r].priority, r)):
            p = self._pending[rid]
            target = self._pick_replica(outstanding)
            if target is None:
                continue
            prompt = p.prompt + [int(t) for t in p.delivered]
            remaining = p.max_new - len(p.delivered)
            try:
                self._clients[target].submit(rid, prompt, remaining,
                                             p.eos, p.priority)
            except Exception:  # noqa: BLE001 — transport gave up; retry
                continue       # next round
            p.replica = target
            p.placed_at = time.monotonic()
            outstanding[target] = outstanding.get(target, 0) + 1
            self._routed_counter(target).inc()
            self._m_place_wait.observe(p.placed_at - p.submitted_at)
            placed.append(rid)
        for rid in placed:
            self._queue.remove(rid)

    def _shed(self):
        if len(self._queue) <= self.max_queue:
            return
        # only shed under GENUINE saturation, never during fleet boot
        # and never while some serving replica could still take work
        # (e.g. a placement that lost its transport round retries next
        # step instead of being rejected)
        if self._unscraped() \
                or self._pick_replica(self._outstanding()) is not None:
            return
        # lowest priority goes first; newest first within a priority
        order = sorted(self._queue,
                       key=lambda r: (self._pending[r].priority, -r))
        while len(self._queue) > self.max_queue and order:
            rid = order.pop(0)
            self._queue.remove(rid)
            p = self._pending[rid]
            self._m_shed.inc()
            self._resolve(p, list(p.delivered), "shed", None)

    def _hedge(self):
        if not self.hedge_after_ms:
            return
        now = time.monotonic()
        outstanding = self._outstanding()
        for rid, p in self._pending.items():
            if p.done or p.replica is None or p.hedge is not None \
                    or p.delivered or p.placed_at is None:
                continue
            if (now - p.placed_at) * 1e3 < float(self.hedge_after_ms):
                continue
            target = self._pick_replica(outstanding,
                                        exclude={p.replica})
            if target is None:
                continue
            try:
                self._clients[target].submit(rid, p.prompt, p.max_new,
                                             p.eos, p.priority)
            except Exception:  # noqa: BLE001 — transport gave up
                continue
            p.hedge = target
            p.hedged = True
            outstanding[target] = outstanding.get(target, 0) + 1
            self._m_hedges.inc()

    def _recover_lost(self):
        now = time.monotonic()
        for name, rep in self.replicas.items():
            if name in self._lost:
                continue
            reason = None
            if not rep.alive and rep.state == "dead":
                reason = "crash"
            elif rep.alive and rep.state in ("serving", "draining"):
                snap = self._last_scrape.get(name)
                if snap and now - snap["ts"] > self.wedge_timeout_s:
                    reason = "wedge"
            elif not rep.alive and rep.state == "drained":
                # parked cleanly; recover any straggler assignments
                # (a submit that raced the drain into a dead inbox)
                self._recover_assignments(name, "drain", rep)
                continue
            if reason is None:
                continue
            if rep.alive:
                rep.kill()  # unstick the wedge; thread exits
            self._lost.add(name)
            self._recover_assignments(name, reason, rep)

    def _recover_assignments(self, name, reason, rep):
        """Fail over every unresolved request assigned to `name`:
        harvest finished results first, recover partial tokens from
        the carcass, then continuation-resubmit (completed prefix
        deduped) or finish straight from the prefix."""
        try:
            for res in rep.pop_results():
                self._handle(res)
        except Exception:  # noqa: BLE001 — best-effort harvest
            pass
        try:
            carcass = {e["rid"]: e for e in rep.export_inflight()}
        except Exception:  # noqa: BLE001 — carcass unreadable: resubmit
            carcass = {}   # from scratch (still correct, just slower)
        for rid, p in list(self._pending.items()):
            if p.done:
                continue
            hit = False
            if p.replica == name:
                p.replica = None
                hit = True
            if p.hedge == name:
                p.hedge = None
                hit = True
            if not hit:
                continue
            p.failovers += 1
            self._failover_counter(name, reason).inc()
            ent = carcass.get(rid)
            if ent and len(ent.get("tokens") or []) > len(p.delivered):
                p.delivered = [int(t) for t in ent["tokens"]]
            if p.replica is not None or p.hedge is not None:
                continue  # the other leg is still running it
            if rid in self._queue:
                continue
            if not self._finish_from_prefix(p):
                self._queue.append(rid)
