"""FleetAutoscaler — SLO-driven elastic capacity for the serving
fleet.

Closes the control loop ROADMAP item 3 names between four shipped
subsystems (docs/robustness.md "Elastic autoscaling & overload
control"):

- **When** — the router's multi-window SLO burn-rate alerts (round 12:
  short AND long window must both burn, so a blip never scales) plus
  the adaptive overload controller's ``degraded`` flag decide
  scale-OUT; scale-IN waits for every objective's error budget to
  recover AND the fleet to run demonstrably idle (router queue empty,
  per-replica outstanding under ``scale_in_util``, history-plane
  placement p99 back under the overload target) for a full
  ``recovery_hold_s`` — hysteresis on top of per-direction cooldowns,
  so the controller never flaps. A scale decision inside
  ``flap_window_s`` of the OPPOSITE decision still executes (the
  capacity need is real) but counts ``fleet_autoscale_flaps_total`` —
  the canary gate fails on ANY flap, which is the "never flaps"
  contract made enforceable.
- **Scale-out execution** — ``spawn_fn(index)`` builds a fresh
  replica (the builder owns ``ServingEngine.warmup()`` — the round-14
  warm-boot contract); the autoscaler then holds it OUTSIDE the fleet
  until its first heartbeat reports ``state=serving`` AND ``warmed``
  (the supervisor's boot gate, applied pre-adoption so the router's
  placement boot gate never stalls the live fleet on a booting
  newcomer), and only then ``router.adopt_replica``\\ s it. The
  compile counts frozen at adoption are exported via ``spawned`` —
  the chaos drill's "a new replica takes traffic with zero new
  steady-state traces" assertion.
- **Scale-in execution** — pick the least-loaded serving replica
  (largest name on ties — deterministic),
  ``supervisor.mark_retiring`` it (exactly-one-owner: the supervisor
  must not read the coming silence/death as a crash and respawn it),
  then ``router.retire`` (hedge legs cancelled first, then drain:
  in-flight finishes token-exact, queued bounces and re-places) and
  ``router.remove_replica`` once drained with zero unresolved
  assignments — zero lost or duplicated requests, journal-anchored. A
  drain stuck past ``retire_timeout_s`` is killed and removed through
  the normal failover harvest (still exactly-once by rid).
- **Every decision** is journaled into the router's WAL
  (``scale_out`` / ``scale_in`` records via ``journal_event`` — a
  successor router surfaces them from ``reconcile()["autoscale"]``)
  and flight-dumped (``fleet_scale_out`` / ``fleet_scale_in``), so a
  crash mid-scale-event is recoverable and explainable.

``poll()`` is driven from the same control thread as
``FleetRouter.step()`` (and ``FleetSupervisor.poll()``), with an
injectable ``now`` for deterministic tests; ``watch()`` wraps the
common loop. Metrics land in the router's registry; the cached
``snapshot()`` rollup rides ``router.health()["autoscale"]`` (and the
``tools/fleet_top.py`` AUTOSCALER panel). ``tools/fleet_replay.py
--knob autoscale.<param>`` scores a policy offline against a recorded
traffic archive.

Env knobs (defaults when the ctor arg is None; catalogue in
docs/observability.md): ``PADDLE_TPU_AUTOSCALE_MIN`` /
``PADDLE_TPU_AUTOSCALE_MAX`` (fleet size bounds),
``PADDLE_TPU_AUTOSCALE_COOLDOWN_S`` (per-direction decision spacing),
``PADDLE_TPU_AUTOSCALE_HOLD_S`` (recovery hold before a scale-in).
"""
from __future__ import annotations

import collections
import os
import time

__all__ = ["FleetAutoscaler"]


def _env_float(name, default):
    v = os.environ.get(name)
    return float(default) if v in (None, "") else float(v)


def _env_int(name, default):
    v = os.environ.get(name)
    return int(default) if v in (None, "") else int(v)


class FleetAutoscaler:
    """Elastic scale-out/in controller over a FleetRouter.

    router: the FleetRouter to scale (its SLO tracker, overload
        controller, history plane and journal are the inputs; its
        dynamic-membership verbs are the actuators).
    spawn_fn: ``spawn_fn(index) -> replica`` — builds one NEW replica
        (unique name, engine warmed via ``warmup()``) each time the
        controller scales out. The replica is adopted only after its
        warm-boot heartbeat; a spawn that raises counts as a failed
        scale-out and respects the cooldown.
    supervisor: optional FleetSupervisor — scale-in victims are
        ``mark_retiring``-ed there BEFORE the drain so the supervision
        loop never resurrects a replica the autoscaler is removing.
    registry: metrics destination (default: the router's registry).
    min_replicas / max_replicas: fleet size bounds (env defaults
        PADDLE_TPU_AUTOSCALE_MIN=1 / PADDLE_TPU_AUTOSCALE_MAX=8).
    scale_out_cooldown_s / scale_in_cooldown_s: minimum spacing after
        a same-direction decision (env default
        PADDLE_TPU_AUTOSCALE_COOLDOWN_S=5; scale-in defaults to 3x
        the scale-out cooldown — adding capacity should be eager,
        removing it reluctant).
    recovery_hold_s: how long the recovered/idle condition must hold
        continuously before a scale-in (env default
        PADDLE_TPU_AUTOSCALE_HOLD_S=3).
    budget_floor: every SLO objective's ``budget_remaining`` must be
        at least this before a scale-in (burnt budget = no shrinking).
    scale_in_util: max mean per-replica outstanding/queue-limit
        utilization considered "idle enough" to shrink.
    boot_timeout_s: spawn -> warm-boot-heartbeat budget; past it the
        newcomer is killed and the scale-out counts as failed.
    retire_timeout_s: drain -> removable budget; past it the victim
        is killed and removed through the failover harvest.
    flap_window_s: opposite-direction decisions closer than this
        count as flaps (``fleet_autoscale_flaps_total``).
    """

    def __init__(self, router, spawn_fn, *, supervisor=None,
                 registry=None, min_replicas=None, max_replicas=None,
                 scale_out_cooldown_s=None, scale_in_cooldown_s=None,
                 recovery_hold_s=None, budget_floor=0.25,
                 scale_in_util=0.25, boot_timeout_s=60.0,
                 retire_timeout_s=60.0, flap_window_s=30.0):
        self.router = router
        self.spawn_fn = spawn_fn
        self.supervisor = supervisor
        self.min_replicas = _env_int("PADDLE_TPU_AUTOSCALE_MIN", 1) \
            if min_replicas is None else int(min_replicas)
        self.max_replicas = _env_int("PADDLE_TPU_AUTOSCALE_MAX", 8) \
            if max_replicas is None else int(max_replicas)
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas({self.min_replicas}) <= "
                f"max_replicas({self.max_replicas})")
        cd = _env_float("PADDLE_TPU_AUTOSCALE_COOLDOWN_S", 5.0)
        self.scale_out_cooldown_s = cd if scale_out_cooldown_s is None \
            else float(scale_out_cooldown_s)
        self.scale_in_cooldown_s = 3.0 * self.scale_out_cooldown_s \
            if scale_in_cooldown_s is None else float(scale_in_cooldown_s)
        self.recovery_hold_s = _env_float(
            "PADDLE_TPU_AUTOSCALE_HOLD_S", 3.0) \
            if recovery_hold_s is None else float(recovery_hold_s)
        self.budget_floor = float(budget_floor)
        self.scale_in_util = float(scale_in_util)
        self.boot_timeout_s = float(boot_timeout_s)
        self.retire_timeout_s = float(retire_timeout_s)
        self.flap_window_s = float(flap_window_s)

        self.state = "steady"     # steady | booting | retiring
        self._pending_rep = None  # the newcomer awaiting its boot gate
        self._boot_deadline = None
        self._boot_started = None
        self._victim = None       # the replica draining toward removal
        self._retire_deadline = None
        self._last_out_at = None
        self._last_in_at = None
        self._recovered_since = None
        self._spawn_seq = 0
        self.spawned = []         # (replica, frozen compile counts at
        #                           adoption) — the zero-new-traces
        #                           assertion's ground truth
        self._events = collections.deque(maxlen=128)
        self._health = {}

        self.registry = registry if registry is not None \
            else router.registry
        reg = self.registry
        self._m_events = {}
        self._m_boots = {}
        self._m_flaps = reg.counter(
            "fleet_autoscale_flaps_total",
            help="scale decisions inside flap_window_s of the "
                 "opposite decision (controller oscillation — "
                 "canary-gated at ANY increase)")
        self._g_replicas = reg.gauge(
            "fleet_autoscale_replicas",
            help="replicas under autoscaler management (fleet "
                 "members + the one mid-boot)")
        # pre-export at 0 so history/canary gates can diff the series
        # at any two instants (the sentinel-counter convention)
        self._event_counter("out", "slo_burn")
        self._event_counter("in", "recovered")
        self._m_flaps.inc(0)
        router.autoscaler = self
        self._refresh(time.monotonic())

    # -- metrics -----------------------------------------------------------

    def _event_counter(self, direction, reason):
        from .router import labeled_counter
        return labeled_counter(
            self.registry, self._m_events, "fleet_autoscale_events_total",
            "autoscaler decisions/outcomes by direction and reason",
            direction=direction, reason=reason)

    def _bootmode_counter(self, mode):
        from .router import labeled_counter
        return labeled_counter(
            self.registry, self._m_boots, "fleet_boots_total",
            "warm boots adopted into rotation, by boot path (aot = "
            "restored from a serving artifact, traced = full Python "
            "trace + compile)", mode=mode)

    # -- control loop ------------------------------------------------------

    def poll(self, now=None):
        """One autoscale round; drive it from the router's control
        thread (``router.step(); sup.poll(); asc.poll()``). Returns
        the (event, detail) transitions this round — events:
        scale_out_started, scaled_out, boot_failed, scale_in_started,
        scaled_in, scale_in_forced."""
        now = time.monotonic() if now is None else float(now)
        events = []
        if self.state == "booting":
            self._poll_booting(now, events)
        elif self.state == "retiring":
            self._poll_retiring(now, events)
        else:
            self._decide(now, events)
        self._refresh(now)
        return events

    def watch(self, until, timeout_s=60.0, poll_s=0.005):
        """Drive ``router.step() + supervisor.poll() + poll()`` until
        ``until()`` is truthy (or raise on timeout) — the common
        elastic-drill loop."""
        deadline = time.monotonic() + float(timeout_s)
        while not until():
            self.router.step()
            if self.supervisor is not None:
                self.supervisor.poll()
            self.poll()
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"autoscaler watch timed out after {timeout_s}s")
            time.sleep(poll_s)

    # -- decision ----------------------------------------------------------

    def _live(self):
        """Fleet members currently servable (or booting back): what
        the size bounds count. Retiring/lost/quarantined members are
        already on their way out."""
        out = []
        for name, rep in self.router.replicas.items():
            if name == self._victim or name in self.router._lost \
                    or getattr(rep, "quarantined", False):
                continue
            out.append(name)
        return out

    def _overloaded(self):
        """The scale-out signal: any SLO multi-window burn pair
        firing (short AND long — round 12's alert shape), or the
        overload controller's standing-queue degraded flag. Returns
        the reason string or None."""
        alerts = self.router.slo_alerting
        if alerts:
            return "slo_burn:" + ",".join(alerts)
        if self.router.degraded:
            return "degraded"
        return None

    def _recovered(self):
        """The scale-in signal: alerts clear, budgets back above the
        floor, and the fleet demonstrably idle — router queue empty,
        mean outstanding utilization under ``scale_in_util``, and the
        history plane's recent placement p99 (when available) back
        under the overload target. Trend + budget, not a point
        sample; _decide additionally requires this to HOLD for
        recovery_hold_s."""
        r = self.router
        if r.slo_alerting or r.degraded or r._queue:
            return False
        for rep in r._slo_state.values():
            br = rep.get("budget_remaining")
            if br is not None and br < self.budget_floor:
                return False
        live = self._live()
        if not live:
            return False
        outstanding = r._outstanding()
        util = sum(outstanding.get(n, 0) for n in live) \
            / (len(live) * max(r.replica_queue_limit, 1))
        if util > self.scale_in_util:
            return False
        hist = getattr(r, "history", None)
        if hist is not None and r._overload_target_s is not None:
            try:
                p99 = hist.quantile_over_time(
                    "fleet_placement_wait_seconds", 0.99,
                    max(self.recovery_hold_s, 1.0))
            except Exception:  # noqa: BLE001 — trend is advisory
                p99 = None
            if p99 is not None and p99 > r._overload_target_s:
                return False
        return True

    def _decide(self, now, events):
        reason = self._overloaded()
        if reason is not None:
            self._recovered_since = None
            if len(self._live()) >= self.max_replicas:
                return
            if self._last_out_at is not None and \
                    now - self._last_out_at < self.scale_out_cooldown_s:
                return
            self._start_scale_out(now, reason, events)
            return
        if not self._recovered():
            self._recovered_since = None
            return
        if self._recovered_since is None:
            self._recovered_since = now
        if now - self._recovered_since < self.recovery_hold_s:
            return
        if len(self._live()) <= self.min_replicas:
            return
        if self._last_in_at is not None and \
                now - self._last_in_at < self.scale_in_cooldown_s:
            return
        self._start_scale_in(now, events)

    def _flap_check(self, now, direction):
        prev = self._last_in_at if direction == "out" \
            else self._last_out_at
        if prev is not None and now - prev < self.flap_window_s:
            self._m_flaps.inc()
            return True
        return False

    # -- scale-out ---------------------------------------------------------

    def _start_scale_out(self, now, reason, events):
        idx = self._spawn_seq
        self._spawn_seq += 1
        flap = self._flap_check(now, "out")
        self._last_out_at = now
        try:
            rep = self.spawn_fn(idx)
        except Exception as e:  # noqa: BLE001 — a failed spawn is a
            #                     failed scale-out, not a dead loop
            self._event_counter("out", "spawn_error").inc()
            self._note(now, "boot_failed", replica=None,
                       reason=f"spawn_error: {type(e).__name__}: {e}")
            events.append(("boot_failed", f"spawn#{idx}"))
            return
        self._pending_rep = rep
        self._boot_started = now
        self._boot_deadline = now + self.boot_timeout_s
        self.state = "booting"
        self._event_counter(
            "out", reason.split(":", 1)[0]).inc()
        self.router.journal_event("scale_out", replica=rep.name,
                                  reason=reason, flap=flap)
        self._note(now, "scale_out_started", replica=rep.name,
                   reason=reason, flap=flap)
        events.append(("scale_out_started", rep.name))

    def _poll_booting(self, now, events):
        rep = self._pending_rep
        snap = None
        try:
            snap = rep.scrape()
        except Exception:  # noqa: BLE001 — no heartbeat yet
            snap = None
        if snap and snap.get("state") == "serving" \
                and snap.get("warmed", True):
            # warm-boot gate passed: the newcomer joins the fleet with
            # its compile counts FROZEN — real traffic after this
            # point must trace nothing new (the supervisor picks the
            # name up automatically on its next poll)
            try:
                frozen = rep.compile_counts() if hasattr(
                    rep, "compile_counts") \
                    else rep.engine.compile_counts()
            except Exception:  # noqa: BLE001 — counts are assertion fuel
                frozen = None
            self.router.adopt_replica(rep)
            self.spawned.append((rep, frozen))
            self._pending_rep = None
            self._boot_deadline = None
            self.state = "steady"
            boot_s = now - self._boot_started
            # boot-path accounting: aot (restored from a serving
            # artifact) vs traced — the autoscale_smoke latency
            # assertion and the fleet_top BOOT column both read this
            bi = snap.get("boot") or {}
            mode = str(bi.get("mode") or "traced")
            self._bootmode_counter(mode).inc()
            self._router_flight("fleet_scale_out", {
                "replica": rep.name, "boot_s": round(boot_s, 6),
                "boot_mode": mode,
                "fleet_size": len(self._live())})
            self._note(now, "scaled_out", replica=rep.name,
                       boot_s=round(boot_s, 6), boot_mode=mode)
            events.append(("scaled_out", rep.name))
            return
        dead = not getattr(rep, "alive", True)
        if dead or now > self._boot_deadline:
            reason = "exit_at_boot" if dead else "boot_timeout"
            try:
                rep.kill()
            except Exception:  # noqa: BLE001 — already gone
                pass
            self._event_counter("out", reason).inc()
            self._note(now, "boot_failed", replica=rep.name,
                       reason=reason)
            events.append(("boot_failed", rep.name))
            self._pending_rep = None
            self._boot_deadline = None
            self.state = "steady"

    # -- scale-in ----------------------------------------------------------

    def _pick_victim(self):
        """Least-loaded serving member; ties retire the LARGEST name
        (deterministic). None when nothing is eligible."""
        r = self.router
        outstanding = r._outstanding()
        cands = []
        for name in self._live():
            rep = r.replicas[name]
            if not rep.alive or rep.state != "serving":
                continue
            cands.append(name)
        if not cands:
            return None
        return max(cands,
                   key=lambda n: (-outstanding.get(n, 0), n))

    def _start_scale_in(self, now, events):
        victim = self._pick_victim()
        if victim is None:
            return
        flap = self._flap_check(now, "in")
        self._last_in_at = now
        self._victim = victim
        self._retire_deadline = now + self.retire_timeout_s
        self.state = "retiring"
        # ownership handoff FIRST: from here the supervisor must not
        # resurrect the victim whatever its process does
        if self.supervisor is not None:
            self.supervisor.mark_retiring(victim)
        self.router.retire(victim)
        self._event_counter("in", "recovered").inc()
        self.router.journal_event("scale_in", replica=victim,
                                  reason="recovered", flap=flap)
        self._router_flight("fleet_scale_in", {
            "replica": victim, "fleet_size": len(self._live()),
            "flap": flap})
        self._note(now, "scale_in_started", replica=victim, flap=flap)
        events.append(("scale_in_started", victim))

    def _poll_retiring(self, now, events):
        name = self._victim
        rep = self.router.replicas.get(name)
        if rep is None:
            # someone else removed it — done either way
            self._victim = None
            self.state = "steady"
            return
        outstanding = self.router._outstanding().get(name, 0)
        drained = not rep.alive and rep.state in ("drained", "dead")
        if drained and outstanding == 0:
            self.router.remove_replica(name)
            self._victim = None
            self.state = "steady"
            self._note(now, "scaled_in", replica=name)
            events.append(("scaled_in", name))
            return
        if now > self._retire_deadline:
            # a wedged drain must not pin the controller: kill the
            # victim and remove it through the failover harvest —
            # in-flight work continuation-resubmits, still
            # exactly-once by rid
            try:
                rep.kill()
            except Exception:  # noqa: BLE001 — already gone
                pass
            try:
                self.router.remove_replica(name)
            except RuntimeError:
                # the kill has not landed yet (a worker inside an
                # uninterruptible stall outlives kill()'s bounded
                # join) — stay in `retiring` and re-attempt next
                # poll instead of crashing the control loop
                return
            self._event_counter("in", "forced").inc()
            self._note(now, "scale_in_forced", replica=name,
                       outstanding=outstanding)
            events.append(("scale_in_forced", name))
            self._victim = None
            self.state = "steady"

    # -- accounting --------------------------------------------------------

    def _note(self, now, event, **detail):
        self._events.append(dict(detail, event=event,
                                 t=round(now, 6)))

    def _router_flight(self, tag, extra):
        try:
            self.router._flight_dump(tag, dict(
                extra, autoscale=self.snapshot()))
        except Exception:  # noqa: BLE001 — postmortems are best-effort
            pass

    def _refresh(self, now):
        live = self._live()
        self._g_replicas.set(
            len(live) + (1 if self._pending_rep is not None else 0))
        last = self._events[-1] if self._events else None
        self._health = {
            "state": self.state,
            "replicas": len(live),
            "min": self.min_replicas, "max": self.max_replicas,
            "booting": None if self._pending_rep is None
            else self._pending_rep.name,
            "retiring": self._victim,
            "recovered_for_s": None if self._recovered_since is None
            else round(now - self._recovered_since, 6),
            "last_decision": None if last is None else dict(last),
            "events": len(self._events)}

    def snapshot(self):
        """Cached rollup for ``router.health()["autoscale"]`` and the
        fleet_top AUTOSCALER panel (health() runs on exporter HTTP
        threads — this must stay a cheap dict copy)."""
        return dict(self._health)

    def health(self):
        """Live controller state + the bounded decision log — what an
        operator reads when asking "why did the fleet just grow"."""
        return dict(self.snapshot(),
                    decisions=[dict(e) for e in self._events])
