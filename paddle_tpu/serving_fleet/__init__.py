"""Fault-tolerant multi-replica serving fleet.

A host-side layer over N ServingEngine replicas: health-routed load
balancing, crash/wedge failover with completed-prefix dedup,
tail-latency hedging, graceful drain/rejoin through the resilience
preemption seam, and priority load shedding — all chaos-testable on
CPU via resilience.faults (replica_crash / replica_wedge /
replica_slow / scrape_timeout / flaky_transport) and all host-side
bookkeeping, so every replica's zero-recompile contract survives the
whole failure model.

- InprocReplica:  one engine + worker thread behind a transport seam
                  (replica.py; a subprocess replica speaks the same
                  verbs over a wire)
- ReplicaClient:  idempotent-by-rid transport with seeded-jitter
                  retry (client.py)
- FleetRouter:    global queue, scrape-scored placement, failover/
                  hedging/drain/shed + its own MetricsRegistry and
                  /metrics endpoint (router.py)

See docs/robustness.md ("Fleet serving") for the contracts and
docs/observability.md for the fleet_* metric catalogue. Chaos suite:
tests/test_fleet_serving.py (pytest -m chaos); campaign stage
fleet_chaos_smoke.
"""
from .client import ReplicaClient  # noqa: F401
from .replica import InprocReplica, ReplicaCrash  # noqa: F401
from .router import FleetRouter  # noqa: F401

__all__ = ["FleetRouter", "InprocReplica", "ReplicaClient",
           "ReplicaCrash"]
