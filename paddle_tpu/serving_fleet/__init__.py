"""Fault-tolerant multi-replica serving fleet.

A host-side layer over N ServingEngine replicas: health-routed load
balancing, crash/wedge failover with completed-prefix dedup,
tail-latency hedging, graceful drain/rejoin through the resilience
preemption seam, and priority load shedding — all chaos-testable on
CPU via resilience.faults (replica_crash / replica_wedge /
replica_slow / scrape_timeout / flaky_transport) and all host-side
bookkeeping, so every replica's zero-recompile contract survives the
whole failure model.

- InprocReplica:  one engine + worker thread behind a transport seam
                  (replica.py; a subprocess replica speaks the same
                  verbs over a wire). The response plane is
                  at-least-once with explicit acks: results are
                  retained until the router durably processed them,
                  so a router crash cannot lose a finished request
- ProcReplica:    the same verbs across a REAL process boundary
                  (proc.py + proc_child.py): one ServingEngine per OS
                  subprocess, length-prefixed checksummed JSONL over
                  pipes (the journal's framing), streamed partial
                  tokens for SIGKILL-grade failover, per-incarnation
                  result stamping, warm-boot respawn
- FleetSupervisor: self-healing replica lifecycle (supervisor.py):
                  OS-level crash detection, seeded-backoff respawn,
                  health-gated warm-boot rejoin, crash-loop circuit
                  breaker with quarantine + cooldown, `retiring`
                  exemption for autoscaler-owned scale-ins
- FleetAutoscaler: SLO-driven elastic capacity (autoscaler.py):
                  scale out on multi-window burn alerts / standing
                  overload, scale in on recovered budget + idle trend
                  with hysteresis + cooldowns, warm-boot-gated
                  adoption, drain->remove retirement, every decision
                  journaled + flight-dumped (fleet_autoscale_*)
- ReplicaClient:  idempotent-by-rid transport with seeded-jitter
                  retry (client.py)
- Journal:        the router's write-ahead request journal
                  (journal.py): append-only checksummed JSONL
                  segments, atomic COMPLETE-marker rotation, torn-
                  tail-tolerant replay, journal_* disk-fault seams —
                  FleetRouter.recover() replays it to re-adopt a
                  still-live fleet after a router crash/preemption
                  with token-exact, exactly-once continuation
- FleetRouter:    global queue, scrape-scored placement, failover/
                  hedging/drain/shed + its own MetricsRegistry,
                  distributed tracing (one causally-linked span tree
                  per request across router/transport/replicas, with
                  per-hop latency attribution via trace_report), SLO
                  burn-rate accounting (fleet_slo_* gauges), and a
                  full /metrics+/healthz+/report+/traces endpoint
                  (router.py)

See docs/robustness.md ("Fleet serving") for the contracts and
docs/observability.md for the fleet_* metric catalogue and the
"Distributed tracing & SLOs" guide. Chaos suites:
tests/test_fleet_serving.py + tests/test_fleet_tracing.py (pytest -m
chaos); campaign stage fleet_chaos_smoke (metrics_diff canary-gated
against tools/golden/fleet_chaos_metrics.json).
"""
from .autoscaler import FleetAutoscaler  # noqa: F401
from .client import ReplicaClient  # noqa: F401
from .journal import Journal, JournalCrash, JournalError  # noqa: F401
from .proc import FrameReader, ProcReplica  # noqa: F401
from .replica import InprocReplica, ReplicaCrash  # noqa: F401
from .router import FleetRouter, RouterCrash  # noqa: F401
from .supervisor import FleetSupervisor  # noqa: F401

__all__ = ["FleetAutoscaler", "FleetRouter", "FleetSupervisor",
           "FrameReader", "InprocReplica", "Journal", "JournalCrash",
           "JournalError", "ProcReplica", "ReplicaClient",
           "ReplicaCrash", "RouterCrash"]
