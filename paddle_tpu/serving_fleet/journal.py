"""Write-ahead request journal — the router's durable memory.

Rounds 11-12 made replicas expendable; the router was left as the
single point of failure: the admission queue, rid ledger, delivered-
prefix continuations and resolved-result buffer all lived in router
memory, so a router crash lost every request the fleet had accepted.
This module journals every request lifecycle transition the router
owns (accepted → placed → delivered-prefix watermarks →
resolved/shed/expired → retired) to an append-only on-disk log that a
fresh router replays to re-adopt the fleet (``FleetRouter.recover``;
docs/robustness.md "Router durability & recovery").

Disk format — built for torn tails, not trust:

- **Segments**: ``wal-<NNNNNN>.jsonl`` files; the highest-numbered
  FINALIZED segment is active (finalized = a ``.complete`` sidecar
  via the shared io/atomic COMPLETE-marker discipline). Appends go to
  the active segment only.
- **Records**: one line each — ``<len:8hex> <crc32:8hex> <payload>``
  where payload is compact JSON. A line that is short, fails its
  length, fails its checksum, or does not parse is a torn record:
  replay DROPS it (counted in ``torn_tail_drops``) and resyncs at the
  next newline, so a crash mid-append costs at most the record being
  written, never the journal.
- **Rotation**: when the active segment outgrows
  ``segment_max_bytes`` (and at every recovery), the owner passes a
  snapshot of its live state and the journal writes a NEW segment
  (header + snapshot records) through io.atomic's write-then-rename +
  marker path — the same discipline io/checkpoint.py finalizes
  checkpoints with — then deletes older segments. Compaction and
  crash-safety in one move: the new segment is readable or the old
  one still is, never neither.

Fault seams (resilience.faults; consulted ONLY in the append path,
with the journal's own append sequence number as the seam step, so a
chaos test pins a fault to an exact record):

- ``journal_torn_write`` — the frame is written truncated
  (``keep_bytes`` payload, default half) and ``JournalCrash`` raises:
  the process died mid-append, tearing the tail. Everything earlier
  is durable; replay drops the torn record.
- ``journal_io_error``  — the append raises ``JournalError``
  (transient disk failure); nothing is written. The router retries
  non-admission records from a backlog; an admission (``accepted``)
  append failure rejects the submit — durability is the admission
  contract.
- ``journal_slow_fsync`` — the fsync path sleeps ``seconds`` (stalls
  surface in step latency, not corruption).

Metrics (``fleet_journal_*`` in the router's registry, catalogue in
docs/observability.md): appends, bytes, fsyncs, errors, rotations,
replay_records, torn_tail_drops (+ the router's
fleet_journal_recovered_requests_total).
"""
from __future__ import annotations

import json
import math
import os
import re
import time
import zlib

from ..io import atomic
from ..resilience import faults

__all__ = ["Journal", "JournalCrash", "JournalError", "reconcile",
           "replay"]

_SEG_RE = re.compile(r"^wal-(\d{6})\.jsonl$")
_FORMAT = 1


class JournalError(RuntimeError):
    """An append could not be made durable (injected
    ``journal_io_error`` or a real OSError from the disk). The record
    was NOT written; the caller decides whether to retry (lifecycle
    records) or reject the operation (admission records)."""


class JournalCrash(JournalError):
    """Injected stand-in for the process dying MID-append
    (``journal_torn_write``): a truncated frame is on disk and no
    further writes will ever happen from this incarnation. Raised out
    of the router's step so the chaos test can abandon the router
    exactly where a real crash would have."""


def _scrub(obj):
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _scrub(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_scrub(v) for v in obj]
    return obj


def _frame(rec):
    """One length-prefixed, checksummed line for `rec`."""
    try:
        payload = json.dumps(rec, separators=(",", ":"),
                             allow_nan=False)
    except ValueError:
        payload = json.dumps(_scrub(rec), separators=(",", ":"),
                             allow_nan=False)
    raw = payload.encode("utf-8")
    crc = zlib.crc32(raw) & 0xFFFFFFFF
    return b"%08x %08x " % (len(raw), crc) + raw + b"\n"


def _parse_line(line):
    """Record dict for one frame line, or None when torn/corrupt."""
    if len(line) < 19 or line[8:9] != b" " or line[17:18] != b" ":
        return None
    try:
        n = int(line[:8], 16)
        crc = int(line[9:17], 16)
    except ValueError:
        return None
    raw = line[18:]
    if len(raw) != n or (zlib.crc32(raw) & 0xFFFFFFFF) != crc:
        return None
    try:
        rec = json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return None
    return rec if isinstance(rec, dict) else None


def _segments(directory):
    """[(num, path)] ascending for every wal segment in `directory`."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        m = _SEG_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    out.sort()
    return out


def _pick_segment(directory):
    """The segment replay trusts: the newest FINALIZED one (its head
    — header + any rotation snapshot — was written atomically, so
    only its appended tail can be torn). Falls back to the newest
    unmarked segment rather than refusing to recover at all."""
    segs = _segments(directory)
    marked = [(n, p) for n, p in segs if atomic.has_marker(p)]
    if marked:
        return marked[-1]
    return segs[-1] if segs else (None, None)


def replay(directory):
    """Parse the journal under `directory`.

    Returns ``(records, stats)`` — the valid records of the chosen
    segment in append order, and
    ``{"segment", "replay_records", "torn_tail_drops", "sealed"}``.
    Torn/corrupt lines are dropped and counted, never raised on: a
    journal that took a crash mid-append must still replay everything
    before the tear."""
    num, path = _pick_segment(directory)
    stats = {"segment": None if num is None else os.path.basename(path),
             "replay_records": 0, "torn_tail_drops": 0, "sealed": False}
    records = []
    if path is None:
        return records, stats
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return records, stats
    for line in data.split(b"\n"):
        if not line:
            continue
        rec = _parse_line(line)
        if rec is None:
            stats["torn_tail_drops"] += 1
            continue
        if rec.get("kind") == "sealed":
            stats["sealed"] = True
        records.append(rec)
        stats["replay_records"] += 1
    return records, stats


def reconcile(records):
    """Fold replayed records into per-rid terminal state — the pure
    half of recovery (fuzz-tested against truncation at every byte;
    FleetRouter._adopt reconciles this against harvested replica
    state).

    Returns ``{"requests": {rid: {...}}, "retired": set,
    "cancelled": set, "next_rid", "sealed", "preempted",
    "autoscale": [scale_out/scale_in/brownout records]}``. A request
    entry carries everything a continuation resubmit needs: prompt,
    budget, eos, priority, wall-clock deadline, the journaled
    delivered prefix (the dedup boundary), last journaled placement
    (+ its prefix anchor and any hedge leg to orphan-cancel), failover
    count, and — for resolved-but-unretired rids — the full result
    for exactly-once re-delivery. Retired rids stay retired whatever
    replays after them; a journaled cancel intent survives into the
    ``cancelled`` set."""
    reqs = {}
    retired = set()
    cancelled = set()
    autoscale = []
    out = {"requests": reqs, "retired": retired,
           "cancelled": cancelled, "next_rid": 0,
           "sealed": False, "preempted": False,
           "autoscale": autoscale}

    def ent(rid):
        return reqs.setdefault(int(rid), {
            "prompt": None, "max_new": 0, "eos": None, "priority": 0,
            "tenant": None,
            "deadline_epoch": None, "submitted_epoch": None,
            "delivered": [], "replica": None, "placed_prefix": None,
            "placed_incarnation": None, "hedge": None, "failovers": 0,
            "resolved": None})

    for rec in records:
        kind = rec.get("kind")
        if kind == "header":
            out["next_rid"] = max(out["next_rid"],
                                  int(rec.get("next_rid", 0)))
        elif kind in ("accepted", "snap_req"):
            rid = rec.get("rid")
            if rid is None or rec.get("prompt") is None \
                    or int(rid) in retired:
                continue
            e = ent(rid)
            e["prompt"] = [int(t) for t in rec["prompt"]]
            e["max_new"] = int(rec.get("max_new", 0))
            e["eos"] = rec.get("eos")
            e["priority"] = int(rec.get("priority", 0))
            e["tenant"] = rec.get("tenant")
            e["deadline_epoch"] = rec.get("deadline_epoch")
            e["submitted_epoch"] = rec.get("submitted_epoch")
            if kind == "snap_req":
                e["delivered"] = [int(t)
                                  for t in rec.get("delivered") or []]
                e["replica"] = rec.get("replica")
                e["placed_prefix"] = rec.get("placed_prefix")
                e["placed_incarnation"] = rec.get("placed_incarnation")
                e["hedge"] = rec.get("hedge")
                e["failovers"] = int(rec.get("failovers", 0))
        elif kind == "placed":
            if rec.get("rid") in reqs:
                e = reqs[int(rec["rid"])]
                e["replica"] = rec.get("replica")
                e["placed_prefix"] = rec.get("prefix")
                # which incarnation of that name holds the leg — a
                # recovered router treats a bumped incarnation as a
                # FRESH engine (the journaled leg died with the old
                # one), never as "still running"
                e["placed_incarnation"] = rec.get("incarnation")
        elif kind == "delivered":
            rid = rec.get("rid")
            if rid in reqs:
                toks = [int(t) for t in rec.get("tokens") or []]
                if len(toks) > len(reqs[int(rid)]["delivered"]):
                    reqs[int(rid)]["delivered"] = toks
        elif kind == "failover":
            rid = rec.get("rid")
            if rid in reqs:
                reqs[int(rid)]["failovers"] += 1
                reqs[int(rid)]["replica"] = None
                reqs[int(rid)]["placed_prefix"] = None
                reqs[int(rid)]["placed_incarnation"] = None
        elif kind in ("resolved", "snap_done"):
            res = rec.get("result")
            if not isinstance(res, dict) or "id" not in res:
                continue
            rid = int(res["id"])
            if rid in retired:
                # a backlog-flushed `resolved` can land AFTER the
                # rid's `retired` record — resurrecting it here would
                # re-deliver a result the client already took
                continue
            e = ent(rid)
            e["resolved"] = res
            e["replica"] = None
        elif kind == "cancel":
            if rec.get("rid") is not None:
                cancelled.add(int(rec["rid"]))
        elif kind == "hedged":
            if rec.get("rid") in reqs:
                reqs[int(rec["rid"])]["hedge"] = rec.get("replica")
        elif kind == "retired":
            for rid in rec.get("rids") or []:
                retired.add(int(rid))
                reqs.pop(int(rid), None)
        elif kind == "sealed":
            out["sealed"] = True
        elif kind == "preempt":
            out["preempted"] = True
        elif kind in ("scale_out", "scale_in", "brownout"):
            # autoscale/overload decision records: kept verbatim so a
            # successor (and its autoscaler) can see the scale event
            # the dead router was mid-way through. A per-rid brownout
            # record additionally clamps the reinstated budget — the
            # degraded promise survives the crash (the request must
            # not resurrect with its full pre-brownout budget).
            rid = rec.get("rid")
            if kind == "brownout" and rid is not None \
                    and int(rid) in reqs \
                    and rec.get("max_new") is not None:
                e = reqs[int(rid)]
                e["max_new"] = min(int(e["max_new"]),
                                   int(rec["max_new"]))
            autoscale.append(dict(rec))
    if reqs:
        out["next_rid"] = max(out["next_rid"], max(reqs) + 1)
    if retired:
        out["next_rid"] = max(out["next_rid"], max(retired) + 1)
    return out


class Journal:
    """Append-only write-ahead log under one directory.

    directory: created if missing; one active segment at a time.
    segment_max_bytes: ``needs_rotation`` turns True past this — the
        OWNER rotates (it holds the live-state snapshot compaction
        needs); the journal never rotates behind its back.
    fsync_every: fsync the active segment every N appends (1 = every
        record, the smallest crash window; rotation and seal always
        fsync regardless).
    registry: MetricsRegistry for the ``fleet_journal_*`` series
        (None = unmetered).
    """

    def __init__(self, directory, *, segment_max_bytes=1 << 20,
                 fsync_every=1, registry=None):
        self.dir = os.path.abspath(str(directory))
        os.makedirs(self.dir, exist_ok=True)
        self.segment_max_bytes = int(segment_max_bytes)
        self.fsync_every = max(int(fsync_every), 1)
        self._seq = 0          # append seam step (this incarnation)
        self._fsyncs = 0
        self._unsynced = 0
        self._crashed = False  # torn-write seam fired: writes are dead
        self.sealed = False
        self._m = {}
        if registry is not None:
            for name, help_ in (
                    ("appends", "journal records appended"),
                    ("bytes", "journal bytes appended"),
                    ("fsyncs", "journal fsync calls"),
                    ("errors", "journal append/fsync failures"),
                    ("rotations", "journal segment rotations"),
                    ("replay_records", "records replayed at recovery"),
                    ("torn_tail_drops",
                     "torn/corrupt records dropped at replay")):
                self._m[name] = registry.counter(
                    f"fleet_journal_{name}_total", help=help_)
        num, path = _pick_segment(self.dir)
        if path is None:
            path = self._create_segment(1, [])
        self._active = path
        self._f = open(path, "ab")
        self._size = os.path.getsize(path)
        # torn-tail repair: a segment that took a crash mid-append
        # ends without a newline. Terminate that line NOW, or the
        # first record this incarnation appends would concatenate
        # onto the torn bytes and be silently unreplayable — an
        # acked-but-unjournaled hole if the process dies again before
        # the recovery rotate() compacts the segment.
        if self._size:
            with open(path, "rb") as rf:
                rf.seek(-1, os.SEEK_END)
                if rf.read(1) != b"\n":
                    self._f.write(b"\n")
                    self._f.flush()
                    self._size += 1

    # -- metrics ----------------------------------------------------------

    def _inc(self, name, n=1):
        c = self._m.get(name)
        if c is not None and n:
            c.inc(n)

    # -- append path ------------------------------------------------------

    @property
    def active_path(self):
        return self._active

    @property
    def needs_rotation(self):
        return self._size >= self.segment_max_bytes

    def append(self, kind, **fields):
        """Durably append one record. Raises JournalError when the
        disk REJECTED the append with nothing written (the injected
        ``journal_io_error`` — transient, retryable), JournalCrash
        when the write is in an unknowable state: the torn-write seam,
        or a REAL write/fsync OSError. After a real failure the
        journal is dead (fsyncgate semantics — a failed fsync leaves
        durability unknowable, so pretending to continue would let
        acked state diverge from disk); the owner should crash and
        recover, which replays whatever actually landed."""
        if self._crashed:
            raise JournalCrash("journal is dead after a torn write")
        self._seq += 1
        seq = self._seq
        rec = {"kind": str(kind), "ts": round(time.time(), 6)}
        rec.update(fields)
        frame = _frame(rec)
        p = faults.pull("journal_io_error", seq)
        if p is not None:
            self._inc("errors")
            raise JournalError(
                f"EIO: injected journal_io_error (append seq {seq})")
        p = faults.pull("journal_torn_write", seq)
        if p is not None:
            keep = int(p.get("keep_bytes", max(len(frame) // 2, 1)))
            self._write(frame[:max(min(keep, len(frame) - 1), 1)],
                        fsync=True)
            self._crashed = True
            raise JournalCrash(
                f"injected journal_torn_write (append seq {seq}): "
                f"process died mid-record")
        self._write(frame, fsync=None)
        self._inc("appends")
        self._inc("bytes", len(frame))
        return rec

    def _write(self, data, fsync):
        """fsync=None → honor the fsync_every cadence; True → force.
        Every append is flushed THROUGH the user-space buffer (a
        process crash must cost at most the record mid-write, not a
        buffer of acknowledged ones); fsync_every only trades power-
        cut durability for speed."""
        try:
            self._f.write(data)
            self._f.flush()
            self._size += len(data)
            self._unsynced += 1
            if fsync or (fsync is None
                         and self._unsynced >= self.fsync_every):
                self._fsync()
        except JournalError:
            raise
        except OSError as e:
            self._inc("errors")
            self._crashed = True
            raise JournalCrash(
                f"journal write failed (journal dead): {e}") from e

    def _fsync(self):
        self._fsyncs += 1
        faults.maybe_sleep("journal_slow_fsync", self._fsync_step())
        try:
            self._f.flush()
            os.fsync(self._f.fileno())
        except OSError as e:
            # fsyncgate: after a failed fsync the kernel may have
            # dropped the dirty pages — durability of EVERYTHING since
            # the last good fsync is unknowable. The only honest move
            # is to declare the journal dead and let recovery replay
            # what actually landed.
            self._inc("errors")
            self._crashed = True
            raise JournalCrash(
                f"journal fsync failed (journal dead): {e}") from e
        self._unsynced = 0
        self._inc("fsyncs")

    def _fsync_step(self):
        return self._fsyncs

    def flush(self):
        """Force the unsynced tail to disk (preemption grace windows,
        close). No-op when everything already landed."""
        if self._crashed:
            return
        if self._unsynced:
            self._fsync()

    def seal(self):
        """Append the clean-shutdown marker and fsync — the
        preemption contract: a SIGTERM'd router seals before exit so
        its successor knows the journal tail is complete, not torn.
        Later appends are still legal (results resolving inside the
        grace window keep journaling); idempotent."""
        if self.sealed or self._crashed:
            return
        self.append("sealed")
        self.flush()
        self.sealed = True

    def close(self):
        try:
            self.flush()
        except JournalError:
            pass
        try:
            self._f.close()
        except OSError:
            pass

    # -- rotation (shared io/atomic discipline) ---------------------------

    def _seg_path(self, num):
        return os.path.join(self.dir, f"wal-{num:06d}.jsonl")

    def _create_segment(self, num, records, next_rid=0):
        """Write segment `num` (header + `records`) atomically and
        finalize it with the .complete sidecar — the checkpoint
        COMPLETE-marker discipline, reused byte for byte: the rename
        is the commit point, the marker is the replay-eligibility
        claim."""
        head = {"kind": "header", "format": _FORMAT, "segment": num,
                "next_rid": int(next_rid), "ts": round(time.time(), 6)}
        data = b"".join([_frame(head)] + [_frame(r) for r in records])
        path = self._seg_path(num)
        atomic.atomic_replace(path, data)
        atomic.write_marker(atomic.marker_path(path),
                            {"segment": num, "records": len(records),
                             "time": time.time()})
        return path

    def rotate(self, snapshot_records, next_rid=0):
        """Compact: open segment N+1 holding `snapshot_records` (the
        owner's live unresolved/undelivered state), then drop older
        segments. Crash-safe at every point — until the new segment's
        marker lands, replay still picks the old one."""
        if self._crashed:
            return None
        segs = _segments(self.dir)
        num = (segs[-1][0] if segs else 0) + 1
        try:
            self.flush()
        except JournalError:
            pass
        path = self._create_segment(num, list(snapshot_records),
                                    next_rid=next_rid)
        try:
            self._f.close()
        except OSError:
            pass
        self._f = open(path, "ab")
        self._active = path
        self._size = os.path.getsize(path)
        self._unsynced = 0
        for n, old in segs:
            if old == path:
                continue
            for victim in (old, atomic.marker_path(old)):
                try:
                    os.remove(victim)
                except OSError:
                    pass
        self._inc("rotations")
        return path

    # -- replay (classmethod conveniences) --------------------------------

    replay = staticmethod(replay)
    reconcile = staticmethod(reconcile)
