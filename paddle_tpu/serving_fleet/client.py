"""Idempotent-by-rid replica transport client with jittered retry.

The router never talks to a replica's transport directly — every
request-plane op (submit / cancel / poll) goes through a
``ReplicaClient`` that

- retries transient transport failures on the resilience.retry
  ladder, with SEEDED jitter (each replica's client gets its own
  ``jitter_seed``, so N clients retrying the same fleet-wide blip
  de-synchronize instead of thundering back in lockstep — and any one
  schedule still replays bit-identically under its seed);
- stays safe to retry because submits are idempotent BY FLEET RID at
  the replica (a duplicate delivery of the same rid is dropped), so
  the classic "ack lost after delivery" uncertainty cannot duplicate
  a request or its tokens.

The ``flaky_transport`` fault kind drills both halves: by default it
raises BEFORE delivery (retry resends, nothing duplicated); with
payload ``after=1`` it delivers and THEN raises (ack lost — the retry
double-delivers and the rid dedup must absorb it). Target one replica
with payload ``replica=<name>``.
"""
from __future__ import annotations

from ..resilience import faults
from ..resilience.retry import RetryStats, call_with_retries, \
    is_transient

__all__ = ["ReplicaClient"]


class ReplicaClient:
    """Request-plane client for one replica transport.

    replica: the transport (InprocReplica or anything with
        enqueue/pop_results).
    retries/base_delay/max_delay: the bounded backoff ladder.
    jitter/jitter_seed: seeded backoff stretch (resilience.retry.
        backoff_schedule) — pass a distinct seed per replica client.
    stats: RetryStats to accumulate into (default: own).
    """

    def __init__(self, replica, *, retries=3, base_delay=0.005,
                 max_delay=0.25, jitter=0.5, jitter_seed=0,
                 stats=None):
        self.replica = replica
        self.retries = int(retries)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self.jitter_seed = int(jitter_seed)
        self.stats = stats if stats is not None else RetryStats()
        self._op = 0

    def _call(self, fn, *args):
        """One transport op under the retry ladder + flaky seam."""
        self._op += 1
        op_id = self._op
        name = getattr(self.replica, "name", None)

        def send():
            p = faults.pull("flaky_transport", op_id,
                            match={"replica": name})
            if p is not None and not p.get("after"):
                raise faults.TransientError(
                    f"UNAVAILABLE: injected flaky_transport to "
                    f"{name} (op {op_id})")
            out = fn(*args)
            if p is not None and p.get("after"):
                # delivered, ack lost: the retry re-delivers and the
                # replica's rid idempotency must absorb the duplicate
                raise faults.TransientError(
                    f"UNAVAILABLE: injected flaky_transport ack loss "
                    f"to {name} (op {op_id})")
            return out

        return call_with_retries(
            send, retries=self.retries, base_delay=self.base_delay,
            max_delay=self.max_delay, retryable=is_transient,
            stats=self.stats, jitter=self.jitter,
            jitter_seed=self.jitter_seed)

    # -- verbs -----------------------------------------------------------

    def submit(self, rid, prompt, max_new_tokens, eos_token_id=None,
               priority=0, deadline_ms=None, trace=None, tenant=None):
        """Deliver one request (idempotent by rid at the replica).
        deadline_ms (remaining wall budget), trace (the dtrace
        context — hop budget already decremented by the caller) and
        tenant (the usage-attribution label, observability.tenancy)
        ride an optional trailing extras dict, so the wire shape
        stays compatible with pre-tracing replicas."""
        op = ["submit", rid, list(prompt), int(max_new_tokens),
              eos_token_id, int(priority)]
        if deadline_ms is not None or trace is not None \
                or tenant is not None:
            op.append({"deadline_ms": deadline_ms, "trace": trace,
                       "tenant": tenant})
        self._call(self.replica.enqueue, tuple(op))

    def cancel(self, rid):
        self._call(self.replica.enqueue, ("cancel", rid))

    def poll(self):
        """Fetch the replica's unacked finished-request dicts. Safe to
        retry AND safe to lose the response: results are retained at
        the replica until ack() — the half of exactly-once the request
        plane's rid idempotency cannot give."""
        return self._call(self.replica.pop_results)

    def ack(self, seqs):
        """Retire delivered results (by ``_rseq``) at the replica.
        Idempotent; the router calls this only once a result is
        processed — and, when journaling, durably journaled — so a
        crash before the ack re-surfaces the result to the recovered
        router instead of losing it."""
        if seqs:
            self._call(self.replica.ack, list(seqs))
