"""FleetSupervisor — the self-healing control loop over replica
lifecycle.

Production TPU serving runs one engine per isolated worker process
with an EXTERNAL supervisor replacing dead workers (the
Gemma-on-Cloud-TPU deployment shape, PAPERS.md). The FleetRouter
already keeps *requests* alive through a replica death (failover with
prefix dedup); this module keeps the *fleet* alive: it watches every
replica's OS process status and scrape heartbeats, respawns dead ones
on a seeded exponential backoff, gates the respawn back into rotation
on a healthy warm-boot heartbeat, and — when a replica keeps dying —
trips a crash-loop circuit breaker instead of respawning forever.

Per-replica state machine::

    serving ──death──▶ backoff ──delay──▶ booting ──healthy hb──▶ serving
       ▲                  ▲                  │ exit / gate timeout
       │                  └──────────────────┘        (a "down")
       └──cooldown, trial boot── quarantined ◀──N downs in window──┘

    any state ──mark_retiring()──▶ retiring (terminal here; purged
                                   once the router removes the name)

``retiring`` is the autoscaler ownership handoff: a replica the
FleetAutoscaler is scaling in (draining toward
``router.remove_replica``) is EXPECTED to stop heartbeating and then
die — the supervisor must not read that as a crash and resurrect it
(nor spend a quarantine half-open trial on it). Exactly one owner
wins: once marked, the supervisor never kills, respawns or trial-boots
the name again; the state purges when the name leaves the router.

- **Crash detection** is OS-level (``rep.alive`` false + state
  ``dead`` — a SIGKILL'd subprocess, a crashed worker thread) plus an
  optional supervisor-side heartbeat timeout for deployments where
  the router's wedge detector is not in the loop.
- **Backoff** delays come from ``resilience.retry.backoff_schedule``
  with a per-replica seed derived from ``(seed, name)`` — the whole
  respawn schedule is a pure function of the seed (chaos tests replay
  it bit-identically; different replicas de-synchronize).
- **Boot gate**: a respawned replica re-enters rotation
  (``router.reinstate``) only once a fresh-incarnation heartbeat
  reports ``state=serving`` AND ``warmed`` — the warm-boot contract:
  the child pre-traced its prefill buckets + decode program
  (``ServingEngine.warmup``), so traffic after the gate runs under
  frozen compile counts. A boot that exceeds ``boot_timeout_s`` is
  killed and counted as a failure (the slow-boot drill).
- **Crash-loop breaker**: ``breaker_threshold`` downs inside
  ``breaker_window_s`` quarantine the replica — no more respawns, a
  ``fleet_crash_loop`` flight dump, ``fleet_crash_loops_total``
  increments, and fleet health degrades HONESTLY (the replica shows
  ``quarantined`` in supervisor and router health instead of
  flapping). After ``breaker_cooldown_s`` the breaker half-opens: one
  trial boot; a failure re-trips immediately, a healthy boot re-arms.

``poll()`` is designed to be driven from the same control thread as
``FleetRouter.step()`` (the router stays single-threaded by design);
``watch()`` wraps the common loop. Metrics land in the ROUTER's
registry by default so one ``/metrics`` scrape carries the whole
fleet story (catalogue in docs/observability.md).
"""
from __future__ import annotations

import collections
import time
import zlib

from ..resilience import preemption
from ..resilience.retry import backoff_schedule

__all__ = ["FleetSupervisor"]


class _RepState:
    __slots__ = ("phase", "downs", "streak", "next_attempt",
                 "boot_started", "boot_deadline", "quarantined_at",
                 "half_open", "last_reason")

    def __init__(self):
        self.phase = "serving"
        self.downs = collections.deque()   # monotonic death times
        self.streak = 0                    # consecutive failed boots
        self.next_attempt = None
        self.boot_started = None
        self.boot_deadline = None
        self.quarantined_at = None
        self.half_open = False
        self.last_reason = None


class FleetSupervisor:
    """Self-healing lifecycle manager for a router's replicas.

    router: the FleetRouter whose replicas to supervise (must expose
        ``replicas`` and ``reinstate``; the supervisor never places
        work — request-level failover stays the router's job).
    registry: metrics destination (default: the router's registry).
    seed: master seed; each replica's backoff schedule derives from
        ``crc32(f"{seed}:{name}")`` so it is deterministic per
        (seed, name) and de-synchronized across names.
    backoff_base_s / backoff_max_s / backoff_jitter: the respawn
        delay ladder (``resilience.retry.backoff_schedule``).
    boot_timeout_s: spawn → healthy-heartbeat budget; past it the
        boot is killed and counted as a failure.
    breaker_threshold / breaker_window_s: downs inside the window
        that trip the crash-loop breaker.
    breaker_cooldown_s: quarantine duration before the half-open
        trial boot.
    heartbeat_timeout_s: optional supervisor-side wedge detection —
        a serving replica whose last heartbeat is older than this is
        killed and counted as a down (None = the router's wedge
        detector owns this, the default).
    honor_preemption: freeze respawns while a process-level
        preemption notice is up (the fleet is draining on purpose).
    """

    def __init__(self, router, *, registry=None, seed=0,
                 backoff_base_s=0.05, backoff_max_s=2.0,
                 backoff_jitter=0.5, boot_timeout_s=120.0,
                 breaker_threshold=3, breaker_window_s=30.0,
                 breaker_cooldown_s=60.0, heartbeat_timeout_s=None,
                 honor_preemption=True):
        self.router = router
        self.seed = int(seed)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.backoff_jitter = float(backoff_jitter)
        self.boot_timeout_s = float(boot_timeout_s)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_window_s = float(breaker_window_s)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.honor_preemption = bool(honor_preemption)
        self._st = {name: _RepState() for name in router.replicas}
        self.registry = registry if registry is not None \
            else router.registry
        reg = self.registry
        self._m_respawn = {}
        self._m_bootfail = {}
        self._m_loops = {}
        self._m_bootmode = {}
        self._m_boot = reg.histogram(
            "fleet_boot_seconds",
            help="respawn -> healthy warm-boot heartbeat (the boot "
                 "gate's measure)")
        self._g_quar = reg.gauge(
            "fleet_replicas_quarantined",
            help="replicas parked by the crash-loop breaker")

    # -- metric helpers ----------------------------------------------------

    def _labeled(self, cache, name, help, **labels):
        from .router import labeled_counter
        return labeled_counter(self.registry, cache, name, help,
                               **labels)

    def _respawn_counter(self, replica):
        return self._labeled(
            self._m_respawn, "fleet_respawns_total",
            "replicas respawned and health-gated back into rotation",
            replica=replica)

    def _bootfail_counter(self, replica, reason):
        return self._labeled(
            self._m_bootfail, "fleet_boot_failures_total",
            "respawn attempts that died (exit-at-boot, gate timeout, "
            "spawn error)", replica=replica, reason=reason)

    def _bootmode_counter(self, mode):
        return self._labeled(
            self._m_bootmode, "fleet_boots_total",
            "warm boots adopted into rotation, by boot path (aot = "
            "restored from a serving artifact, traced = full Python "
            "trace + compile)", mode=mode)

    def _loop_counter(self, replica):
        return self._labeled(
            self._m_loops, "fleet_crash_loops_total",
            "crash-loop breaker trips (replica quarantined)",
            replica=replica)

    # -- deterministic backoff --------------------------------------------

    def _backoff_seed(self, name):
        return zlib.crc32(f"{self.seed}:{name}".encode()) & 0xFFFFFFFF

    def backoff_delays(self, name, n):
        """The exact delays the supervisor will wait before respawn
        attempts 1..n of `name` — a pure function of (seed, name), so
        a chaos run's whole respawn schedule replays bit-identically
        and two replicas never thunder in lockstep."""
        return backoff_schedule(int(n), base_delay=self.backoff_base_s,
                                max_delay=self.backoff_max_s,
                                jitter=self.backoff_jitter,
                                jitter_seed=self._backoff_seed(name))

    # -- control loop ------------------------------------------------------

    def poll(self, now=None):
        """One supervision round over every replica; drive it from
        the router's control thread (``router.step(); sup.poll()``).
        Returns the list of (name, event) transitions this round —
        events: down, respawn_scheduled, boot_started, boot_failed,
        respawned, quarantined, rearmed."""
        now = time.monotonic() if now is None else float(now)
        events = []
        # replicas retired from the fleet (router.remove_replica) must
        # not haunt the quarantined gauge / health forever
        for name in [n for n in self._st
                     if n not in self.router.replicas]:
            del self._st[name]
        frozen = self.honor_preemption and preemption.requested()
        for name, rep in list(self.router.replicas.items()):
            st = self._st.setdefault(name, _RepState())
            ph = st.phase
            if ph == "serving":
                self._poll_serving(name, rep, st, now, events)
            elif ph == "backoff":
                if not frozen and st.next_attempt is not None \
                        and now >= st.next_attempt:
                    self._attempt_boot(name, rep, st, now, events)
            elif ph == "booting":
                self._poll_booting(name, rep, st, now, events)
            elif ph == "retiring":
                # autoscaler-owned: an expected death — no hb-timeout
                # kill, no respawn, no half-open trial. Purged above
                # once remove_replica drops the name from the router.
                continue
            elif ph == "quarantined":
                if not frozen and st.quarantined_at is not None \
                        and now - st.quarantined_at \
                        >= self.breaker_cooldown_s:
                    # half-open: one trial boot; a failure re-trips
                    # the breaker immediately
                    st.phase = "backoff"
                    st.half_open = True
                    st.downs.clear()
                    st.next_attempt = now
                    self._set_quarantined(rep, False)
                    events.append((name, "rearmed"))
        self._g_quar.set(sum(1 for s in self._st.values()
                             if s.phase == "quarantined"))
        return events

    def mark_retiring(self, name):
        """Hand ownership of `name` to the autoscaler's scale-in path:
        from now on its drain, silence and death are EXPECTED — the
        supervisor will not kill it on a heartbeat timeout, respawn it
        on death, or spend a quarantine half-open trial on it
        (exactly-one-owner: ``watch()`` must never resurrect a replica
        mid-retirement). Idempotent; the state purges once the router
        drops the name (``remove_replica``). Returns the previous
        phase."""
        st = self._st.setdefault(str(name), _RepState())
        prev = st.phase
        st.phase = "retiring"
        st.next_attempt = None
        st.boot_started = st.boot_deadline = None
        st.half_open = False
        if prev == "quarantined":
            # leaving quarantine for retirement: clear the breaker
            # cosmetics so health shows 'retiring', not a phantom
            # quarantine on a name that is about to disappear
            rep = self.router.replicas.get(name)
            if rep is not None:
                self._set_quarantined(rep, False)
        st.quarantined_at = None
        return prev

    def watch(self, until, timeout_s=60.0, poll_s=0.005):
        """Drive ``router.step() + poll()`` until ``until()`` is
        truthy (or raise on timeout). The common chaos-drill loop."""
        deadline = time.monotonic() + float(timeout_s)
        while not until():
            self.router.step()
            self.poll()
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"supervisor watch timed out after {timeout_s}s")
            time.sleep(poll_s)

    # -- phase handlers ----------------------------------------------------

    def _poll_serving(self, name, rep, st, now, events):
        if not rep.alive and rep.state == "dead":
            self._down(name, rep, st, now, "crash", events)
            return
        if rep.state == "drained":
            return   # operator/preemption drain — not ours to undo
        if self.heartbeat_timeout_s is not None and rep.alive:
            snap = self._safe_scrape(rep)
            if snap and now - snap.get("ts", now) \
                    > float(self.heartbeat_timeout_s):
                rep.kill()
                self._down(name, rep, st, now, "wedge", events)

    def _attempt_boot(self, name, rep, st, now, events):
        try:
            rep.rejoin()   # ProcReplica.respawn / InprocReplica.rejoin
        except Exception:  # noqa: BLE001 — a spawn error is a down
            self._bootfail_counter(name, "spawn_error").inc()
            self._down(name, rep, st, now, "spawn_error", events)
            return
        st.phase = "booting"
        st.boot_started = now
        st.boot_deadline = now + self.boot_timeout_s
        events.append((name, "boot_started"))

    def _poll_booting(self, name, rep, st, now, events):
        if not rep.alive:
            # exit-at-boot: the child died before its hello/heartbeat
            self._bootfail_counter(name, "exit_at_boot").inc()
            self._down(name, rep, st, now, "exit_at_boot", events)
            return
        snap = self._safe_scrape(rep)
        fresh = bool(snap) and snap.get("incarnation") in (
            None, getattr(rep, "incarnation", None))
        if fresh and snap.get("state") == "serving" \
                and snap.get("warmed", True):
            # healthy warm boot: gate it back into rotation
            self._m_boot.observe(now - st.boot_started)
            self._respawn_counter(name).inc()
            # boot-path accounting: did this respawn come up off an
            # AOT serving artifact or the traced path? (heartbeats
            # carry engine.boot_info — absent on pre-artifact builds,
            # which counts as traced)
            bi = snap.get("boot") or {}
            self._bootmode_counter(
                str(bi.get("mode") or "traced")).inc()
            self.router.reinstate(name)
            st.phase = "serving"
            st.streak = 0
            st.half_open = False
            st.boot_started = st.boot_deadline = None
            events.append((name, "respawned"))
            return
        if now > st.boot_deadline:
            # slow boot past the gate: kill it, count the failure
            rep.kill()
            self._bootfail_counter(name, "boot_timeout").inc()
            self._down(name, rep, st, now, "boot_timeout", events)

    def _down(self, name, rep, st, now, reason, events):
        st.last_reason = reason
        st.streak += 1
        st.downs.append(now)
        cut = now - self.breaker_window_s
        while st.downs and st.downs[0] < cut:
            st.downs.popleft()
        events.append((name, "down"))
        if st.half_open or len(st.downs) >= self.breaker_threshold:
            self._quarantine(name, rep, st, now, reason, events)
            return
        delay = self.backoff_delays(name, st.streak)[st.streak - 1]
        st.phase = "backoff"
        st.next_attempt = now + delay
        events.append((name, "respawn_scheduled"))

    def _quarantine(self, name, rep, st, now, reason, events):
        st.phase = "quarantined"
        st.quarantined_at = now
        st.half_open = False
        st.next_attempt = None
        self._loop_counter(name).inc()
        self._set_quarantined(rep, True)
        events.append((name, "quarantined"))
        self._flight_dump(name, rep, st, reason)

    @staticmethod
    def _set_quarantined(rep, flag):
        """Mark the replica object so router health (and operators
        reading it) see the breaker state, not an endlessly 'lost'
        replica."""
        try:
            rep.quarantined = bool(flag)
        except Exception:  # noqa: BLE001 — health cosmetics only
            pass

    def _safe_scrape(self, rep):
        try:
            return rep.scrape()
        except Exception:  # noqa: BLE001 — a failed scrape is just
            return None    # "no news"

    def _flight_dump(self, name, rep, st, reason):
        try:
            from ..observability import flightrec
            flightrec.note("fleet_crash_loop", replica=name,
                           reason=reason, streak=st.streak)
            flightrec.dump("fleet_crash_loop", extra={
                "replica": name, "breaker_reason": reason,
                "downs_in_window": len(st.downs),
                "window_s": self.breaker_window_s,
                "streak": st.streak,
                "incarnation": getattr(rep, "incarnation", None),
                "supervisor": self.health()})
        except Exception:  # noqa: BLE001 — a postmortem write must
            pass           # not take the supervisor down

    # -- introspection -----------------------------------------------------

    def health(self):
        """Per-replica supervision state — what an operator pages on
        when the fleet is degraded: who is quarantined, who is mid-
        backoff and for how much longer, boot failure streaks."""
        now = time.monotonic()
        reps = {}
        for name, st in self._st.items():
            rep = self.router.replicas.get(name)
            reps[name] = {
                "phase": st.phase,
                "alive": None if rep is None else rep.alive,
                "incarnation": getattr(rep, "incarnation", None),
                "streak": st.streak,
                "downs_in_window": len(st.downs),
                "last_reason": st.last_reason,
                "next_attempt_in_s": None if st.next_attempt is None
                or st.phase != "backoff"
                else round(max(st.next_attempt - now, 0.0), 6),
                "quarantined_for_s": None if st.quarantined_at is None
                or st.phase != "quarantined"
                else round(now - st.quarantined_at, 6)}
        # the router's anomaly-sentinel rollup rides the supervisor
        # health too: "who is quarantined" and "is the fleet inside
        # its learned bands" page together — a respawn storm that
        # coincides with a TTFT excursion is one incident, not two
        # dashboards
        sen = getattr(self.router, "sentinel", None)
        return {"replicas": reps,
                "quarantined": sorted(
                    n for n, s in self._st.items()
                    if s.phase == "quarantined"),
                "retiring": sorted(
                    n for n, s in self._st.items()
                    if s.phase == "retiring"),
                "anomaly_alerting": None if sen is None
                else sen.alerting(),
                "breaker": {"threshold": self.breaker_threshold,
                            "window_s": self.breaker_window_s,
                            "cooldown_s": self.breaker_cooldown_s}}
