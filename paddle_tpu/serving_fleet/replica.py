"""One serving replica behind a transport seam — the fleet's unit.

An ``InprocReplica`` owns a ``ServingEngine`` and drives it from a
dedicated daemon worker thread, speaking exactly the verbs a
subprocess/remote replica would speak over a wire:

- ``enqueue(op)``        — submit/cancel commands (the request plane);
- ``pop_results()``      — finished-request dicts (the response
  plane). AT-LEAST-ONCE with explicit acks: every result is retained
  (keyed by a per-replica ``_rseq``) and re-returned by every poll
  until ``ack()``ed, so neither a lost poll response nor a ROUTER
  CRASH between poll and processing can lose a result — the recovered
  router simply polls again. The router acks each result as soon as
  it has processed it (and, when journaling, only once the resolution
  is durable), so retention is transient in steady state;
- ``ack(seqs)``          — drop retained results (idempotent);
- ``scrape()``           — the last published health/metrics snapshot
  (what scraping the round-10 ``/metrics``+``/healthz`` endpoint of a
  real replica process returns);
- ``drain()`` / ``kill()`` / ``rejoin()`` — lifecycle control;
- ``export_inflight()``  — partial tokens of a dead/wedged replica's
  unfinished requests (in a subprocess deployment these facts arrive
  over the streaming token channel; in-process the carcass is
  readable directly).

EVERY engine touch happens on the worker thread: submits and cancels
ride the inbox queue, health is published as an immutable snapshot
under a lock, results are appended under a lock. The router never
calls into the engine of a LIVE replica, so the single-threaded
engine contract holds; ``export_inflight`` is only read once the
worker is provably not running (dead, wedged-asleep, or drained).

Chaos seams (resilience.faults, payload-targeted by replica name —
``inject("replica_crash", replica="r1")``):

- ``replica_crash`` — the worker thread dies at a round boundary
  (consulted only once the replica is BUSY, so an unpinned fault
  deterministically fires mid-decode with partial tokens in flight);
- ``replica_wedge`` — the worker stops heartbeating for ``seconds``
  (router detects via scrape staleness and fails over);
- ``replica_slow``  — host sleep per round (tail-latency/hedging
  drill).

The worker also polls ``resilience.preemption.requested()``: a
process-level SIGTERM drains every replica gracefully through the
same path as ``drain()`` — the fleet analogue of the round-8
checkpoint-and-exit contract.
"""
from __future__ import annotations

import queue
import threading
import time

from ..resilience import faults, preemption

__all__ = ["InprocReplica", "ReplicaCrash"]


class ReplicaCrash(RuntimeError):
    """Injected stand-in for a replica process dying (OOM-kill, chip
    reset, node loss). Raised inside the worker loop; the thread dies
    and the router's failover path takes over."""


class InprocReplica:
    """One ServingEngine + one worker thread + transport-shaped edges.

    name: replica identity (fault targeting, routing labels).
    engine: a ServingEngine this replica takes ownership of driving.
    poll_s: idle-loop sleep (the worker never busy-spins).
    heartbeat_s: min interval between health-snapshot publishes.
    honor_preemption: drain when resilience.preemption.requested()
        (process SIGTERM → every replica drains gracefully).
    """

    def __init__(self, name, engine, *, poll_s=0.001, heartbeat_s=0.01,
                 honor_preemption=True):
        self.name = str(name)
        self.engine = engine
        self.poll_s = float(poll_s)
        self.heartbeat_s = float(heartbeat_s)
        self.honor_preemption = bool(honor_preemption)
        self._inbox = queue.Queue()
        self._out_lock = threading.Lock()
        self._outbox = []
        self._unacked = {}      # _rseq -> result (retained until ack)
        self._emit_seq = 0
        self._health_lock = threading.Lock()
        self._health = {}
        self._accepted = {}     # fleet rid -> engine rid (idempotency)
        self._rid_map = {}      # engine rid -> fleet rid
        self._rid_inc = {}      # engine rid -> incarnation at accept
        self._precancel = set()  # cancel arrived before its submit
        # incarnation: bumped on every rejoin(). Results are stamped
        # with the incarnation their request was ACCEPTED under, so a
        # rejoined worker flushing a pre-crash slot emits results the
        # router's stale-incarnation guard can reject even when the
        # rid has legitimately been re-placed onto this same name.
        self.incarnation = 1
        self._drain = threading.Event()
        self._stop = threading.Event()
        self._round = 0
        self._last_publish = 0.0
        self._state = "serving"
        self.error = None
        self._thread = None
        self._start()

    # -- router-facing transport verbs (never touch the engine) ----------

    @property
    def state(self):
        """serving | draining | drained | dead (worker-written)."""
        return self._state

    @property
    def alive(self):
        return self._thread is not None and self._thread.is_alive()

    def enqueue(self, op):
        """Queue one command for the worker: ("submit", fleet_rid,
        prompt, max_new_tokens, eos_token_id, priority[, extras]) or
        ("cancel", fleet_rid). The optional trailing extras dict
        carries {"deadline_ms", "trace", "tenant"} — the
        distributed-trace context and the tenancy label hop the
        transport here exactly as they would a wire. Submits are
        idempotent by fleet rid — a transport retry that
        double-delivers is absorbed."""
        self._inbox.put(tuple(op))

    def pop_results(self):
        """Every unacked result (fleet-rid-keyed dicts, ``_rseq``
        order). Results move from the outbox into the unacked
        retention map and are RE-returned by every poll until
        ``ack``ed — at-least-once, so a crashed router's successor
        re-harvests whatever the dead incarnation polled but never
        durably processed (the router dedups by resolved rid). Pure
        lock ops — works even after the worker died, which is how a
        drained/crashed replica's last results are harvested."""
        with self._out_lock:
            for r in self._outbox:
                self._unacked[r["_rseq"]] = r
            self._outbox = []
            return [dict(r) for r in sorted(self._unacked.values(),
                                            key=lambda r: r["_rseq"])]

    def ack(self, seqs):
        """Drop retained results by ``_rseq`` (idempotent — a retried
        ack that double-delivers is a no-op)."""
        with self._out_lock:
            for s in seqs:
                self._unacked.pop(s, None)

    def scrape(self):
        """Last published health snapshot (dict copy). The
        ``scrape_timeout`` fault makes this raise a transient
        DEADLINE_EXCEEDED exactly like a real scrape timing out; the
        router keeps routing on its previous snapshot. Deliberately
        NOT retried — the next heartbeat is fresher than a retry."""
        if faults.pull("scrape_timeout", self._round,
                       match={"replica": self.name}) is not None:
            raise faults.TransientError(
                f"DEADLINE_EXCEEDED: injected scrape_timeout "
                f"({self.name})")
        with self._health_lock:
            return dict(self._health)

    def drain(self):
        """Graceful: stop admitting, finish in-flight token-exactly,
        bounce queued work back to the router, then park (state
        'drained'). Idempotent."""
        self._drain.set()

    def kill(self, join_timeout=2.0):
        """Hard stop the worker (wedge recovery). The thread exits at
        its next check — including from inside a wedge sleep."""
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=join_timeout)

    def rejoin(self):
        """Restart a drained/dead replica's worker on the SAME engine,
        so every compiled program carries over — a rejoin costs zero
        recompiles. Leftover in-flight work from a crash (the router
        already failed it over) is cancelled and flushed; the router
        drops those stale results by resolved-rid dedup."""
        if self.alive:
            raise RuntimeError(f"replica {self.name} is still running")
        if self.engine.state == "closed":
            raise RuntimeError("engine is closed — cannot rejoin")
        self.incarnation += 1
        if self.engine.state == "draining":
            self.engine.resume()
        for ent in self.engine.export_inflight():
            self.engine.cancel(ent["rid"])
        while not self.engine.idle:
            for res in self.engine.step():
                self._emit_engine(res)
        # forget the previous incarnation's accepted rids: the router
        # may legitimately re-place a failed-over/bounced rid back
        # HERE, and the idempotency check must not drop it as a
        # duplicate delivery. (_rid_map keeps its old entries — engine
        # rids never repeat, and stale results still need translating
        # so the router can dedup them by resolved rid.)
        self._accepted = {}
        self._precancel = set()
        self._drain = threading.Event()
        self._stop = threading.Event()
        self._state = "serving"
        self.error = None
        self._start()

    def export_inflight(self):
        """Fleet-rid-keyed unfinished-request snapshot off the engine.
        Only valid once the worker is not running (dead/wedged/
        drained) — the failover and requeue paths."""
        out = []
        for ent in self.engine.export_inflight():
            frid = self._rid_map.get(ent["rid"])
            if frid is not None:
                out.append(dict(ent, rid=frid))
        return out

    def compile_counts(self):
        """Transport-shaped compile-count rollup (ProcReplica reads
        these off the child's heartbeats; in-process the engine is
        right here)."""
        return self.engine.compile_counts()

    def unexpected_retraces(self):
        return self.engine.tracer.unexpected_retraces()

    # -- worker thread ----------------------------------------------------

    def _start(self):
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"fleet-replica-{self.name}")
        self._thread.start()

    def _loop(self):
        state_out = "drained"
        try:
            while True:
                if self._stop.is_set():
                    state_out = "dead"
                    self.error = self.error or "killed"
                    break
                self._round += 1
                r = self._round
                busy = not self.engine.idle
                # crash/wedge seams consult only when the replica has
                # work: an unpinned fault fires deterministically
                # "mid-decode" instead of on the first idle round
                if busy:
                    if faults.pull("replica_crash", r,
                                   match={"replica": self.name}) \
                            is not None:
                        raise ReplicaCrash(
                            f"injected replica_crash on {self.name} "
                            f"(round {r})")
                    p = faults.pull("replica_wedge", r,
                                    match={"replica": self.name})
                    if p is not None:
                        self._wedge(float(p.get("seconds", 30.0)))
                        continue
                faults.maybe_sleep("replica_slow", r,
                                   match={"replica": self.name})
                if (self._drain.is_set()
                        or (self.honor_preemption
                            and preemption.requested())):
                    if self.engine.state == "serving":
                        self.engine.drain()
                    self._state = "draining"
                self._pump_inbox()
                if not self.engine.idle:
                    for res in self.engine.step():
                        self._emit_engine(res)
                elif self._state == "draining":
                    break  # drained: engine empty, inbox bounced
                else:
                    time.sleep(self.poll_s)
                self._publish()
        except ReplicaCrash as e:
            state_out = "dead"
            self.error = str(e)
        except Exception as e:  # noqa: BLE001 — a worker bug is a crash
            state_out = "dead"
            self.error = f"{type(e).__name__}: {e}"
        self._state = state_out
        self._publish(force=True)

    def _wedge(self, seconds):
        """No heartbeats, no progress — what a stuck process looks
        like from outside. kill() releases it early."""
        t_end = time.monotonic() + seconds
        while time.monotonic() < t_end and not self._stop.is_set():
            time.sleep(0.005)

    def _pump_inbox(self):
        while True:
            try:
                op = self._inbox.get_nowait()
            except queue.Empty:
                return
            if op[0] == "submit":
                _, frid, prompt, max_new, eos, prio = op[:6]
                extras = op[6] if len(op) > 6 else {}
                if frid in self._accepted:
                    continue  # idempotent: duplicate delivery dropped
                if frid in self._precancel:
                    self._precancel.discard(frid)
                    self._emit({"id": frid, "tokens": [],
                                "status": "cancelled"})
                    continue
                if self._state != "serving" \
                        or self.engine.state != "serving":
                    # not admitting: bounce so the router re-places it
                    self._emit({"id": frid, "tokens": [],
                                "status": "bounced"})
                    continue
                erid = self.engine.submit(
                    prompt, max_new, eos, priority=prio,
                    deadline_ms=extras.get("deadline_ms"),
                    trace=extras.get("trace"),
                    tenant=extras.get("tenant"))
                self._accepted[frid] = erid
                self._rid_map[erid] = frid
                self._rid_inc[erid] = self.incarnation
            elif op[0] == "cancel":
                erid = self._accepted.get(op[1])
                if erid is not None:
                    self.engine.cancel(erid)
                else:
                    self._precancel.add(op[1])

    def _emit_engine(self, res):
        """Translate an engine result (engine rid) to the fleet rid
        and publish it. A TERMINAL result also retires the rid from
        the idempotency ledger: the request is no longer in flight
        here, so a later re-submit of the same rid (a recovered
        router re-placing work it distrusts, or re-queueing after
        cancelling a stale leg) must be accepted as a fresh run, not
        silently dropped — the router's resolved-rid dedup absorbs
        any duplicate result the at-least-once edge can produce."""
        frid = self._rid_map.get(res["id"])
        if frid is None:
            return  # engine-local request (warmup) — not fleet-owned
        if res.get("status") in ("ok", "expired", "cancelled"):
            self._accepted.pop(frid, None)
        self._emit(dict(res, id=frid),
                   inc=self._rid_inc.get(res["id"]))

    def _emit(self, res, inc=None):
        with self._out_lock:
            self._emit_seq += 1
            self._outbox.append(dict(
                res, replica=self.name,
                incarnation=self.incarnation if inc is None else inc,
                _rseq=self._emit_seq))

    def _publish(self, force=False):
        now = time.monotonic()
        if not force and now - self._last_publish < self.heartbeat_s:
            return
        self._last_publish = now
        h = self.engine.health()
        qw = self.engine.registry.get("serve_queue_wait_seconds")
        p99 = qw.quantile(0.99) if qw is not None and qw.count else 0.0
        snap = {"replica": self.name, "state": self._state,
                "engine_state": h.get("state"), "ts": now,
                "round": self._round,
                "incarnation": self.incarnation,
                "warmed": bool(h.get("warmed", True)),
                "queued": h["queued"], "running": h["running"],
                "free_pages": h["free_pages"],
                "total_pages": h["total_pages"],
                "page_occupancy": h["page_occupancy"],
                "page_size": self.engine.page_size,
                "queue_wait_p99_s": round(float(p99 or 0.0), 6),
                "decode_tokens": h["decode_tokens"],
                "tenants_tracked": h.get("tenants_tracked", 0),
                "sampling": h.get("sampling"),
                "prefix_cache": h.get("prefix_cache"),
                "spec": h.get("spec"),
                "mem": h.get("mem"),
                "boot": h.get("boot"),
                "compile_counts": h["compile_counts"]}
        with self._health_lock:
            self._health = snap
