"""Optimizers (ref: python/paddle/optimizer/optimizer.py + per-opt files).

Each optimizer is a *functional core* — ``init_state(params)`` and
``update(params, grads, state, lr, step)`` over pytrees of jax arrays — plus
the reference's eager class API (``opt.step()`` over Parameter.grad). The
Engine/hapi path jits the functional core together with the model's grad
computation into one fused train step (the reference fuses the same way via
its fused_adam / multi_tensor kernels; XLA does the fusion for us).

multi_precision=True keeps fp32 master weights when params are bf16/fp16
(ref: paddle.amp O2 master weights).
"""
from __future__ import annotations

import sys
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..nn.clip import ClipGradBase
from ..tensor import Tensor
from .lr import LRScheduler


def _tree_zeros_like(params):
    return jax.tree_util.tree_map(lambda p: jnp.zeros_like(p), params)


def _sround_bf16(x32, key):
    """Unbiased stochastic rounding fp32 -> bf16: add uniform 16-bit noise
    below the bf16 mantissa cut, then truncate. E[result] == x32, so a
    bf16-stored Adam second moment still accumulates (1-b2)=1e-3 relative
    increments that nearest-rounding would silently drop (they sit below
    bf16's 2^-8 resolution). This is what makes half-width moments usable:
    it halves the optimizer's HBM state traffic (BENCHLOG: 9.9 GB/step at
    gpt3-345M) without biasing the moment estimates.
    ref parity: paddle.optimizer.adamw multi_precision / master-weight
    path (python/paddle/optimizer/adamw.py) — same goal (reduced-precision
    state with fp32 math), TPU-native mechanism."""
    x32 = x32.astype(jnp.float32)
    bits = jax.lax.bitcast_convert_type(x32, jnp.uint32)
    noise = jax.random.bits(key, x32.shape, jnp.uint16).astype(jnp.uint32)
    rounded = jax.lax.bitcast_convert_type(
        ((bits + noise) >> 16).astype(jnp.uint16), jnp.bfloat16)
    # non-finite bit patterns must bypass the noise add: inf + payload
    # truncates to NaN, and uint32 wraparound on negative-NaN patterns
    # flips the sign bit — keep a diverged run's inf recoverable
    return jnp.where(jnp.isfinite(x32), rounded, x32.astype(jnp.bfloat16))


def _store_moment(x32, dtype, key):
    if dtype is None or x32.dtype == dtype:
        return x32
    if dtype == jnp.bfloat16:
        return _sround_bf16(x32, key)
    return x32.astype(dtype)


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None,
                 apply_decay_param_fun=None):
        self._lr = learning_rate
        self._parameter_list = self._normalize_params(parameters)
        if isinstance(weight_decay, (int, float)) or weight_decay is None:
            self._weight_decay = float(weight_decay or 0.0)
        else:  # L1Decay/L2Decay objects expose .coeff
            self._weight_decay = float(getattr(weight_decay, "_coeff",
                                               getattr(weight_decay, "coeff", 0.0)))
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._apply_decay_param_fun = apply_decay_param_fun
        self._step_count = 0
        self._accumulators: Dict = {}
        self._func_state = None
        self._seen_keys = set()
        self._pending_state_leaves = None

    @staticmethod
    def _normalize_params(parameters):
        if parameters is None:
            return None
        plist = list(parameters)
        if plist and isinstance(plist[0], dict):
            # param groups; flatten (per-group lr kept in optimize_attr)
            flat = []
            for group in plist:
                lr_mult = group.get("learning_rate", 1.0)
                wd = group.get("weight_decay", None)
                for p in group["params"]:
                    p.optimize_attr["learning_rate"] = lr_mult
                    if wd is not None:
                        p.optimize_attr["weight_decay"] = \
                            float(getattr(wd, "_coeff", wd))
                    flat.append(p)
            return flat
        return plist

    # -- lr ----------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = float(value)

    def _lr_value(self):
        return self.get_lr()

    # -- functional core (override per optimizer) ---------------------------
    def init_state(self, params):
        return {}

    def update(self, params, grads, state, lr, step):
        raise NotImplementedError

    # -- decoupled/coupled weight decay helpers -----------------------------
    def _decay_mask(self, params):
        """True where weight decay applies (apply_decay_param_fun parity)."""
        fn = self._apply_decay_param_fun
        if fn is None:
            return jax.tree_util.tree_map(lambda _: True, params)
        if isinstance(params, dict):
            return {k: bool(fn(k)) for k in params}
        return jax.tree_util.tree_map(lambda _: True, params)

    # -- eager API ----------------------------------------------------------
    def _param_key(self, p, i):
        """Stable per-parameter key: the parameter's name when it has one
        (Layer.named_parameters assigns the structured path), else a key
        pinned to the object identity — so optimizer state survives steps
        where only a subset of params received grads."""
        if p.name:
            return p.name
        keys = self.__dict__.setdefault("_obj_keys", {})
        k = keys.get(id(p))
        if k is None:
            k = f"param_{i}_{len(keys)}"
            keys[id(p)] = k
        return k

    def step(self):
        params = [p for p in (self._parameter_list or []) if p.trainable]
        pg = [(p, p.grad) for p in params]
        if self._grad_clip is not None and isinstance(self._grad_clip, ClipGradBase):
            clip_in = {i: g._value for i, (p, g) in enumerate(pg) if g is not None
                       and p.need_clip}
            clipped = self._grad_clip.apply(clip_in)
            for i, (p, g) in enumerate(pg):
                if i in clipped:
                    pg[i] = (p, Tensor(clipped[i]))
        keys = [self._param_key(p, i) for i, (p, g) in enumerate(pg)]
        pdict = {k: p._value for k, (p, g) in zip(keys, pg) if g is not None}
        gdict = {k: g._value.astype(p._value.dtype)
                 for k, (p, g) in zip(keys, pg) if g is not None}
        if not pdict:
            self._step_count += 1
            return
        full = {k: p._value for k, (p, g) in zip(keys, pg)}
        if self._func_state is None:
            self._func_state = self.init_state(full)
            self._apply_pending_state()
            self._apply_group_sharded_placement(params)
        else:
            # init slots for params never seen before, keep existing moments
            new_keys = [k for k in full if k not in self._seen_keys]
            if new_keys:
                fresh = self.init_state({k: full[k] for k in new_keys})
                for sk, sub in fresh.items():
                    if isinstance(self._func_state.get(sk), dict):
                        self._func_state[sk].update(sub)
        self._seen_keys = set(full)
        # update() touches only grad-bearing keys this step
        state_view = {sk: ({k: sub[k] for k in pdict if k in sub}
                           if isinstance(sub, dict) else sub)
                      for sk, sub in self._func_state.items()}
        lr = self._lr_value()
        lr_mult = {k: p.optimize_attr.get("learning_rate", 1.0)
                   for k, (p, g) in zip(keys, pg) if k in pdict}
        new_p, new_state = self.update(
            pdict, gdict, state_view, lr, self._step_count + 1,
            lr_mult=lr_mult)
        for sk, sub in new_state.items():
            if isinstance(sub, dict) and isinstance(self._func_state.get(sk), dict):
                self._func_state[sk].update(sub)
            else:
                self._func_state[sk] = sub
        for k, (p, g) in zip(keys, pg):
            if k in new_p:
                p._value = new_p[k]
        self._step_count += 1
        self._mem_report(gdict)

    def _mem_report(self, gdict):
        """Level-set optimizer_state/grads bytes into the process's
        active memory ledger, if one is armed. Guarded on the module
        already being imported: a training loop with no ledger pays a
        dict lookup, not an import, and never creates mem_* series
        (the observability dormancy contract)."""
        mod = sys.modules.get("paddle_tpu.observability.memledger")
        if mod is None:
            return
        try:
            led = mod.active_ledger()
            if led is None:
                return
            led.set_level("optimizer_state",
                          mod.nbytes_of(self._func_state),
                          label=type(self).__name__)
            led.set_level("grads", mod.nbytes_of(gdict),
                          label=type(self).__name__)
        except Exception:  # noqa: BLE001 — accounting must never
            pass           # take a training step down

    def _apply_group_sharded_placement(self, params=None):
        """GroupSharded/ZeRO in the eager loop (ref: the reference's primary
        group_sharded_parallel usage is loss.backward(); opt.step()): place
        optimizer state — and at stage 3 the live parameters — on their
        dp-sharded layout the first time state is materialised."""
        gs = getattr(self, "_group_sharded", None)
        if gs is None or self._func_state is None:
            return
        from ..distributed.fleet.sharding import shard_tree
        self._func_state = shard_tree(self._func_state, gs.mesh, gs.axis)
        if gs.shard_params and params:
            for p in params:
                p._value = shard_tree([p._value], gs.mesh, gs.axis)[0]

    def _apply_pending_state(self):
        pending = getattr(self, "_pending_state_leaves", None)
        if pending is None or self._func_state is None:
            return
        import jax as _jax
        leaves, treedef = _jax.tree_util.tree_flatten(self._func_state)
        if len(pending) == len(leaves):
            self._func_state = _jax.tree_util.tree_unflatten(treedef, pending)
        self._pending_state_leaves = None

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list or []:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()

    # -- state dict (checkpoint/resume) -------------------------------------
    def state_dict(self):
        flat = {}
        if self._func_state is not None:
            leaves, treedef = jax.tree_util.tree_flatten(self._func_state)
            flat["__leaves__"] = [Tensor(l) if isinstance(l, jax.Array) else l
                                  for l in leaves]
        flat["__step__"] = self._step_count
        if isinstance(self._lr, LRScheduler):
            flat["LR_Scheduler"] = self._lr.state_dict()
        return flat

    def set_state_dict(self, state):
        self._step_count = int(state.get("__step__", 0))
        if "LR_Scheduler" in state and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state["LR_Scheduler"])
        if "__leaves__" in state:
            new_leaves = [l._value if isinstance(l, Tensor) else l
                          for l in state["__leaves__"]]
            if self._func_state is not None:
                leaves, treedef = jax.tree_util.tree_flatten(self._func_state)
                if len(new_leaves) == len(leaves):
                    self._func_state = jax.tree_util.tree_unflatten(
                        treedef, new_leaves)
                    return
            # state not built yet (no step taken): stash and apply on the
            # first init_state (both eager step() and Engine honor this)
            self._pending_state_leaves = new_leaves

    # -- helpers shared by subclasses ---------------------------------------
    def _wd_for(self, key, default):
        return default

    def _effective_lr(self, lr, lr_mult, key):
        if lr_mult is None:
            return lr
        return lr * lr_mult.get(key, 1.0)


class SGD(Optimizer):
    """ref: paddle.optimizer.SGD — vanilla + optional (coupled) L2 decay."""

    def update(self, params, grads, state, lr, step, lr_mult=None):
        wd = self._weight_decay

        def upd(k):
            g = grads[k]
            p = params[k]
            if wd:
                g = g + wd * p
            return p - self._effective_lr(lr, lr_mult, k) * g
        return {k: upd(k) for k in params}, state


class Momentum(Optimizer):
    """ref: paddle.optimizer.Momentum (heavy-ball, optional Nesterov)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def init_state(self, params):
        return {"velocity": _tree_zeros_like(params)}

    def update(self, params, grads, state, lr, step, lr_mult=None):
        mu = self._momentum
        wd = self._weight_decay
        new_v, new_p = {}, {}
        for k in params:
            g = grads[k]
            p = params[k]
            if wd:
                g = g + wd * p
            v = mu * state["velocity"][k] + g
            elr = self._effective_lr(lr, lr_mult, k)
            if self._nesterov:
                new_p[k] = p - elr * (g + mu * v)
            else:
                new_p[k] = p - elr * v
            new_v[k] = v
        return new_p, {"velocity": new_v}


class Adam(Optimizer):
    """ref: paddle.optimizer.Adam (bias-corrected, coupled L2 decay)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None, apply_decay_param_fun=None, amsgrad=False,
                 moment_dtype=None, fused_kernel=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name, apply_decay_param_fun)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._amsgrad = amsgrad
        self._decoupled = False
        # one-HBM-pass Pallas update for large fp32 leaves (ref: the
        # CUDA fused adamw_kernel) — r4 step anatomy measured the jnp
        # chain at ~2x its bandwidth floor. Opt-in A/B lever
        # (bench --fused-adamw); ineligible leaves (small, amsgrad,
        # master weights, bf16 moments) keep the jnp path.
        self._fused_kernel = bool(fused_kernel)
        # reduced-precision moment storage (bf16 halves optimizer HBM
        # traffic; math stays fp32, stores use stochastic rounding)
        self._moment_dtype = jnp.dtype(moment_dtype) if moment_dtype else None
        if self._moment_dtype not in (None, jnp.dtype(jnp.bfloat16),
                                      jnp.dtype(jnp.float32)):
            raise ValueError(
                f"moment_dtype={moment_dtype}: only bfloat16 (stochastic "
                "rounding) or float32 are supported")

    def init_state(self, params):
        mdt = self._moment_dtype

        def zeros(p):
            return jnp.zeros(p.shape, mdt or p.dtype)
        st = {"m": jax.tree_util.tree_map(zeros, params),
              "v": jax.tree_util.tree_map(zeros, params)}
        if self._amsgrad:
            # fp32 regardless of moment_dtype: see the vhat note in update()
            st["vhat"] = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
        if self._multi_precision:
            st["master"] = jax.tree_util.tree_map(
                lambda p: p.astype(jnp.float32), params)
        return st

    def update(self, params, grads, state, lr, step, lr_mult=None):
        import zlib
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        wd = self._weight_decay
        decay_fn = self._apply_decay_param_fun
        mdt = self._moment_dtype
        bc1 = 1.0 - b1 ** step
        bc2 = 1.0 - b2 ** step
        skey = None
        if mdt == jnp.bfloat16:
            # per-step, per-parameter keys derived inside the trace: no
            # threading through the Engine signature, identical eager/jit
            skey = jax.random.fold_in(jax.random.PRNGKey(0xAD04), step)
        new_m, new_v, new_p = {}, {}, {}
        new_vhat = {}
        new_master = {}
        use_fused = self._fused_kernel and not self._amsgrad \
            and not self._multi_precision
        if use_fused:
            import jax as _jax
            from ..ops.pallas.fused_adamw import (fused_adamw_supported,
                                                  fused_adamw_update)
            interp = _jax.default_backend() != "tpu"
        for k in params:
            if use_fused and fused_adamw_supported(
                    params[k], state["m"][k], state["v"][k]):
                apply_wd = wd and (decay_fn is None or decay_fn(k))
                elr = self._effective_lr(lr, lr_mult, k)
                new_p[k], new_m[k], new_v[k] = fused_adamw_update(
                    params[k], state["m"][k], state["v"][k], grads[k],
                    elr, bc1, bc2, beta1=b1, beta2=b2, eps=eps,
                    weight_decay=(wd if apply_wd else 0.0),
                    decoupled=self._decoupled, interpret=interp)
                continue
            g = grads[k].astype(jnp.float32)
            p32 = state["master"][k] if self._multi_precision else \
                params[k].astype(jnp.float32)
            apply_wd = wd and (decay_fn is None or decay_fn(k))
            if apply_wd and not self._decoupled:
                g = g + wd * p32
            m = b1 * state["m"][k].astype(jnp.float32) + (1 - b1) * g
            v = b2 * state["v"][k].astype(jnp.float32) + \
                (1 - b2) * jnp.square(g)
            m_hat = m / bc1
            if self._amsgrad:
                vh = jnp.maximum(state["vhat"][k].astype(jnp.float32), v)
                denom = jnp.sqrt(vh / bc2) + eps
            else:
                vh = None
                denom = jnp.sqrt(v / bc2) + eps
            elr = self._effective_lr(lr, lr_mult, k)
            stepv = elr * m_hat / denom
            if apply_wd and self._decoupled:
                stepv = stepv + elr * wd * p32
            p_new32 = p32 - stepv
            if skey is not None:
                kk = jax.random.fold_in(
                    skey, zlib.crc32(k.encode()) & 0x7FFFFFFF)
                k_m, k_v = jax.random.split(kk)
                new_m[k] = _store_moment(m, mdt, k_m)
                new_v[k] = _store_moment(v, mdt, k_v)
            else:
                new_m[k], new_v[k] = m, v
            if vh is not None:
                # vhat stays fp32 even under moment_dtype: AMSGrad's
                # monotone-max invariant turns unbiased rounding noise
                # into an upward ratchet (max acts as a reflecting
                # barrier), silently shrinking the effective lr
                new_vhat[k] = vh
            if self._multi_precision:
                new_master[k] = p_new32
                new_p[k] = p_new32.astype(params[k].dtype)
            else:
                new_p[k] = p_new32.astype(params[k].dtype)
        st = {"m": new_m, "v": new_v}
        if self._amsgrad:
            st["vhat"] = new_vhat
        if self._multi_precision:
            st["master"] = new_master
        return new_p, st


class AdamW(Adam):
    """ref: paddle.optimizer.AdamW — decoupled weight decay."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None,
                 amsgrad=False, moment_dtype=None, fused_kernel=False):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         name, apply_decay_param_fun, amsgrad,
                         moment_dtype=moment_dtype,
                         fused_kernel=fused_kernel)
        self._decoupled = True


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name=name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def init_state(self, params):
        return {"m": _tree_zeros_like(params), "u": _tree_zeros_like(params)}

    def update(self, params, grads, state, lr, step, lr_mult=None):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        wd = self._weight_decay
        new = ({}, {}, {})
        for k in params:
            g = grads[k]
            p = params[k]
            if wd:
                g = g + wd * p
            m = b1 * state["m"][k] + (1 - b1) * g
            u = jnp.maximum(b2 * state["u"][k], jnp.abs(g))
            elr = self._effective_lr(lr, lr_mult, k) / (1 - b1 ** step)
            new[0][k] = p - elr * m / (u + eps)
            new[1][k] = m
            new[2][k] = u
        return new[0], {"m": new[1], "u": new[2]}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name=name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def init_state(self, params):
        return {"moment": jax.tree_util.tree_map(
            lambda p: jnp.full_like(p, self._init_acc), params)}

    def update(self, params, grads, state, lr, step, lr_mult=None):
        wd = self._weight_decay
        new_m, new_p = {}, {}
        for k in params:
            g = grads[k]
            p = params[k]
            if wd:
                g = g + wd * p
            m = state["moment"][k] + jnp.square(g)
            new_p[k] = p - self._effective_lr(lr, lr_mult, k) * g / \
                (jnp.sqrt(m) + self._epsilon)
            new_m[k] = m
        return new_p, {"moment": new_m}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name=name)
        self._epsilon, self._rho = epsilon, rho

    def init_state(self, params):
        return {"avg_sq_grad": _tree_zeros_like(params),
                "avg_sq_update": _tree_zeros_like(params)}

    def update(self, params, grads, state, lr, step, lr_mult=None):
        rho, eps = self._rho, self._epsilon
        wd = self._weight_decay
        n1, n2, np_ = {}, {}, {}
        for k in params:
            g = grads[k]
            p = params[k]
            if wd:
                g = g + wd * p
            asg = rho * state["avg_sq_grad"][k] + (1 - rho) * jnp.square(g)
            upd = g * jnp.sqrt(state["avg_sq_update"][k] + eps) / jnp.sqrt(asg + eps)
            asu = rho * state["avg_sq_update"][k] + (1 - rho) * jnp.square(upd)
            np_[k] = p - self._effective_lr(lr, lr_mult, k) * upd
            n1[k], n2[k] = asg, asu
        return np_, {"avg_sq_grad": n1, "avg_sq_update": n2}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name=name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def init_state(self, params):
        st = {"mean_sq": _tree_zeros_like(params),
              "velocity": _tree_zeros_like(params)}
        if self._centered:
            st["mean_g"] = _tree_zeros_like(params)
        return st

    def update(self, params, grads, state, lr, step, lr_mult=None):
        rho, eps, mu = self._rho, self._epsilon, self._momentum
        wd = self._weight_decay
        new_ms, new_v, new_mg, new_p = {}, {}, {}, {}
        for k in params:
            g = grads[k]
            p = params[k]
            if wd:
                g = g + wd * p
            ms = rho * state["mean_sq"][k] + (1 - rho) * jnp.square(g)
            if self._centered:
                mg = rho * state["mean_g"][k] + (1 - rho) * g
                denom = jnp.sqrt(ms - jnp.square(mg) + eps)
                new_mg[k] = mg
            else:
                denom = jnp.sqrt(ms + eps)
            v = mu * state["velocity"][k] + \
                self._effective_lr(lr, lr_mult, k) * g / denom
            new_p[k] = p - v
            new_ms[k], new_v[k] = ms, v
        st = {"mean_sq": new_ms, "velocity": new_v}
        if self._centered:
            st["mean_g"] = new_mg
        return new_p, st


class Lamb(Optimizer):
    """ref: paddle.optimizer.Lamb — layerwise-adaptive Adam for large batch."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay,
                         grad_clip, multi_precision, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def init_state(self, params):
        return {"m": _tree_zeros_like(params), "v": _tree_zeros_like(params)}

    def update(self, params, grads, state, lr, step, lr_mult=None):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        wd = self._weight_decay
        new_m, new_v, new_p = {}, {}, {}
        for k in params:
            g = grads[k].astype(jnp.float32)
            p = params[k].astype(jnp.float32)
            m = b1 * state["m"][k] + (1 - b1) * g
            v = b2 * state["v"][k] + (1 - b2) * jnp.square(g)
            m_hat = m / (1 - b1 ** step)
            v_hat = v / (1 - b2 ** step)
            r = m_hat / (jnp.sqrt(v_hat) + eps)
            use_wd = wd and (self._exclude_fn is None or not self._exclude_fn(k))
            if use_wd:
                r = r + wd * p
            w_norm = jnp.linalg.norm(p)
            r_norm = jnp.linalg.norm(r)
            trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
            new_p[k] = (p - self._effective_lr(lr, lr_mult, k) * trust * r
                        ).astype(params[k].dtype)
            new_m[k], new_v[k] = m, v
        return new_p, {"m": new_m, "v": new_v}


class NAdam(Adam):
    def update(self, params, grads, state, lr, step, lr_mult=None):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        new_m, new_v, new_p = {}, {}, {}
        for k in params:
            g = grads[k]
            p = params[k]
            if self._weight_decay:
                g = g + self._weight_decay * p
            m = b1 * state["m"][k] + (1 - b1) * g
            v = b2 * state["v"][k] + (1 - b2) * jnp.square(g)
            m_hat = m / (1 - b1 ** (step + 1))
            v_hat = v / (1 - b2 ** step)
            m_bar = b1 * m_hat + (1 - b1) * g / (1 - b1 ** step)
            new_p[k] = p - self._effective_lr(lr, lr_mult, k) * m_bar / \
                (jnp.sqrt(v_hat) + eps)
            new_m[k], new_v[k] = m, v
        return new_p, {"m": new_m, "v": new_v}


class RAdam(Adam):
    def update(self, params, grads, state, lr, step, lr_mult=None):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        rho_inf = 2.0 / (1 - b2) - 1
        new_m, new_v, new_p = {}, {}, {}
        for k in params:
            g = grads[k]
            p = params[k]
            if self._weight_decay:
                g = g + self._weight_decay * p
            m = b1 * state["m"][k] + (1 - b1) * g
            v = b2 * state["v"][k] + (1 - b2) * jnp.square(g)
            m_hat = m / (1 - b1 ** step)
            rho_t = rho_inf - 2 * step * (b2 ** step) / (1 - b2 ** step)
            elr = self._effective_lr(lr, lr_mult, k)
            v_hat = jnp.sqrt(v / (1 - b2 ** step))
            r_num = (rho_t - 4) * (rho_t - 2) * rho_inf
            r_den = (rho_inf - 4) * (rho_inf - 2) * rho_t
            r = jnp.sqrt(jnp.maximum(r_num / r_den, 0.0))
            rect = p - elr * r * m_hat / (v_hat + eps)
            plain = p - elr * m_hat
            new_p[k] = jnp.where(rho_t > 5.0, rect, plain)
            new_m[k], new_v[k] = m, v
        return new_p, {"m": new_m, "v": new_v}


class Rprop(Optimizer):
    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name=name)
        self._lr_range = learning_rate_range
        self._etas = etas

    def init_state(self, params):
        return {"prev_grad": _tree_zeros_like(params),
                "step_size": jax.tree_util.tree_map(
                    lambda p: jnp.full_like(p, float(self.get_lr())), params)}

    def update(self, params, grads, state, lr, step, lr_mult=None):
        eta_m, eta_p = self._etas
        lo, hi = self._lr_range
        new_pg, new_ss, new_p = {}, {}, {}
        for k in params:
            g = grads[k]
            sign = jnp.sign(g * state["prev_grad"][k])
            ss = jnp.clip(jnp.where(sign > 0, state["step_size"][k] * eta_p,
                                    jnp.where(sign < 0,
                                              state["step_size"][k] * eta_m,
                                              state["step_size"][k])), lo, hi)
            g_eff = jnp.where(sign < 0, 0.0, g)
            new_p[k] = params[k] - jnp.sign(g_eff) * ss
            new_pg[k] = g_eff
            new_ss[k] = ss
        return new_p, {"prev_grad": new_pg, "step_size": new_ss}
