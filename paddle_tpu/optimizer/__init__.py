"""paddle_tpu.optimizer (ref: python/paddle/optimizer)."""
from . import lr  # noqa: F401
from .optimizer import (  # noqa: F401
    Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb, Momentum, NAdam, Optimizer,
    RAdam, RMSProp, Rprop, SGD,
)
from .lbfgs import LBFGS  # noqa: F401
