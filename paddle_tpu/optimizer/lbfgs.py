"""LBFGS optimizer (ref: python/paddle/optimizer/lbfgs.py).

Closure-driven quasi-Newton for the eager path: two-loop recursion over a
bounded (s, y) history with optional strong-Wolfe line search (cubic
interpolation). Parameters are flattened into one vector per step so the
history math is a handful of dot products — fine on TPU since each closure
evaluation is the dominant cost.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor import Tensor
from .optimizer import Optimizer

__all__ = ["LBFGS"]


def _flat(tensors):
    return jnp.concatenate([t.reshape(-1) for t in tensors])


def _cubic_interpolate(x1, f1, g1, x2, f2, g2, bounds=None):
    if bounds is not None:
        xmin_bound, xmax_bound = bounds
    else:
        xmin_bound, xmax_bound = (x1, x2) if x1 <= x2 else (x2, x1)
    d1 = g1 + g2 - 3 * (f1 - f2) / (x1 - x2)
    d2_square = d1 ** 2 - g1 * g2
    if d2_square >= 0:
        d2 = d2_square ** 0.5
        if x1 <= x2:
            min_pos = x2 - (x2 - x1) * ((g2 + d2 - d1) / (g2 - g1 + 2 * d2))
        else:
            min_pos = x1 - (x1 - x2) * ((g1 + d2 - d1) / (g1 - g2 + 2 * d2))
        return min(max(min_pos, xmin_bound), xmax_bound)
    return (xmin_bound + xmax_bound) / 2.0


def _strong_wolfe(obj_func, x_init, t, d, f, g, gtd, c1=1e-4, c2=0.9,
                  tolerance_change=1e-9, max_ls=25):
    """torch-style strong-Wolfe line search. obj_func(x, t, d) -> (f, g)."""
    d_norm = float(jnp.abs(d).max())
    g = jnp.array(g)
    f_new, g_new = obj_func(x_init, t, d)
    ls_func_evals = 1
    gtd_new = float(g_new @ d)

    t_prev, f_prev, g_prev, gtd_prev = 0.0, f, g, gtd
    done = False
    ls_iter = 0
    while ls_iter < max_ls:
        if f_new > (f + c1 * t * gtd) or (ls_iter > 1 and f_new >= f_prev):
            bracket = [t_prev, t]
            bracket_f = [f_prev, f_new]
            bracket_g = [g_prev, g_new]
            bracket_gtd = [gtd_prev, gtd_new]
            break
        if abs(gtd_new) <= -c2 * gtd:
            bracket = [t]
            bracket_f = [f_new]
            bracket_g = [g_new]
            done = True
            break
        if gtd_new >= 0:
            bracket = [t_prev, t]
            bracket_f = [f_prev, f_new]
            bracket_g = [g_prev, g_new]
            bracket_gtd = [gtd_prev, gtd_new]
            break
        min_step = t + 0.01 * (t - t_prev)
        max_step = t * 10
        tmp = t
        t = _cubic_interpolate(t_prev, f_prev, gtd_prev, t, f_new, gtd_new,
                               bounds=(min_step, max_step))
        t_prev, f_prev, g_prev, gtd_prev = tmp, f_new, g_new, gtd_new
        f_new, g_new = obj_func(x_init, t, d)
        ls_func_evals += 1
        gtd_new = float(g_new @ d)
        ls_iter += 1
    else:
        bracket = [0, t]
        bracket_f = [f, f_new]
        bracket_g = [g, g_new]
        bracket_gtd = [gtd, gtd_new]

    insuf_progress = False
    low_pos, high_pos = (0, 1) if bracket_f[0] <= bracket_f[-1] else (1, 0)
    while not done and ls_iter < max_ls:
        if abs(bracket[1] - bracket[0]) * d_norm < tolerance_change:
            break
        t = _cubic_interpolate(bracket[0], bracket_f[0], bracket_gtd[0],
                               bracket[1], bracket_f[1], bracket_gtd[1])
        eps = 0.1 * abs(bracket[1] - bracket[0])
        if min(max(bracket) - t, t - min(bracket)) < eps:
            if insuf_progress or t >= max(bracket) or t <= min(bracket):
                t = (max(bracket) - eps if abs(t - max(bracket))
                     < abs(t - min(bracket)) else min(bracket) + eps)
                insuf_progress = False
            else:
                insuf_progress = True
        else:
            insuf_progress = False
        f_new, g_new = obj_func(x_init, t, d)
        ls_func_evals += 1
        gtd_new = float(g_new @ d)
        ls_iter += 1
        if f_new > (f + c1 * t * gtd) or f_new >= bracket_f[low_pos]:
            bracket[high_pos] = t
            bracket_f[high_pos] = f_new
            bracket_g[high_pos] = g_new
            bracket_gtd[high_pos] = gtd_new
            low_pos, high_pos = ((0, 1) if bracket_f[0] <= bracket_f[1]
                                 else (1, 0))
        else:
            if abs(gtd_new) <= -c2 * gtd:
                done = True
            elif gtd_new * (bracket[high_pos] - bracket[low_pos]) >= 0:
                bracket[high_pos] = bracket[low_pos]
                bracket_f[high_pos] = bracket_f[low_pos]
                bracket_g[high_pos] = bracket_g[low_pos]
                bracket_gtd[high_pos] = bracket_gtd[low_pos]
            bracket[low_pos] = t
            bracket_f[low_pos] = f_new
            bracket_g[low_pos] = g_new
            bracket_gtd[low_pos] = gtd_new

    t = bracket[low_pos] if len(bracket) > 1 else bracket[0]
    f_new = bracket_f[low_pos] if len(bracket) > 1 else bracket_f[0]
    g_new = bracket_g[low_pos] if len(bracket) > 1 else bracket_g[0]
    return f_new, g_new, t, ls_func_evals


class LBFGS(Optimizer):
    """ref: paddle.optimizer.LBFGS — `step(closure)` API; closure clears
    grads, computes the loss, calls backward, and returns the loss."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name=name)
        if max_eval is None:
            max_eval = max_iter * 5 // 4
        self._max_iter = max_iter
        self._max_eval = max_eval
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._history_size = history_size
        self._line_search_fn = line_search_fn
        self._state = {}

    # -- eager plumbing -----------------------------------------------------
    def _params(self):
        # plain Tensors (x.stop_gradient=False) are accepted like the
        # reference; Parameters additionally honor .trainable
        return [p for p in (self._parameter_list or [])
                if getattr(p, "trainable", not p.stop_gradient)]

    def _gather_flat_grad(self):
        wd = self._weight_decay
        gs = {}
        for i, p in enumerate(self._params()):
            g = p._grad_value
            g = (jnp.zeros_like(p._value) if g is None
                 else jnp.asarray(g, p._value.dtype))
            if wd:
                g = g + wd * p._value  # coupled L2, like the reference
            gs[i] = g
        if self._grad_clip is not None:
            gs = self._grad_clip.apply(gs)
        return jnp.concatenate([g.reshape(-1) for g in gs.values()])

    def _set_flat_params(self, flat):
        off = 0
        for p in self._params():
            n = int(p._value.size)
            p._value = flat[off:off + n].reshape(p._value.shape) \
                .astype(p._value.dtype)
            off += n

    def _gather_flat_params(self):
        return _flat([p._value for p in self._params()])

    def _directional_evaluate(self, closure, x, t, d):
        self._set_flat_params(x + t * d)
        loss = closure()
        fv = float(loss._value if isinstance(loss, Tensor) else loss)
        g = self._gather_flat_grad()
        self._set_flat_params(x)
        return fv, g

    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure that "
                             "re-evaluates the model and returns the loss")
        st = self._state
        lr = self.get_lr()

        loss = closure()
        orig_loss = loss
        fv = float(loss._value if isinstance(loss, Tensor) else loss)
        current_evals = 1
        flat_grad = self._gather_flat_grad()
        if float(jnp.abs(flat_grad).max()) <= self._tol_grad:
            return orig_loss

        d = st.get("d")
        t = st.get("t", lr)
        old_sk = st.setdefault("old_sk", [])
        old_yk = st.setdefault("old_yk", [])
        ro = st.setdefault("ro", [])
        prev_flat_grad = st.get("prev_flat_grad")
        h_diag = st.get("h_diag", 1.0)

        prev_fv = None
        n_iter = 0
        while n_iter < self._max_iter:
            n_iter += 1
            st["n_iter_total"] = st.get("n_iter_total", 0) + 1
            if n_iter == 1 and prev_flat_grad is None:
                d = -flat_grad
                h_diag = 1.0
            else:
                y = flat_grad - prev_flat_grad
                s = d * t
                ys = float(y @ s)
                if ys > 1e-10:
                    if len(old_yk) == self._history_size:
                        old_yk.pop(0)
                        old_sk.pop(0)
                        ro.pop(0)
                    old_yk.append(y)
                    old_sk.append(s)
                    ro.append(1.0 / ys)
                    h_diag = ys / float(y @ y)
                num_old = len(old_yk)
                al = [0.0] * num_old
                q = -flat_grad
                for i in range(num_old - 1, -1, -1):
                    al[i] = float(old_sk[i] @ q) * ro[i]
                    q = q - al[i] * old_yk[i]
                d = q * h_diag
                for i in range(num_old):
                    be_i = float(old_yk[i] @ d) * ro[i]
                    d = d + old_sk[i] * (al[i] - be_i)
            prev_flat_grad = flat_grad

            # trial-step rescale applies only on the FIRST-EVER iteration
            # (reference: state n_iter == 1, cumulative across step() calls)
            if st["n_iter_total"] == 1:
                t = min(1.0, 1.0 / float(jnp.abs(flat_grad).sum())) * lr
            else:
                t = lr

            gtd = float(flat_grad @ d)
            if gtd > -self._tol_change:
                break

            if self._line_search_fn is not None:
                if self._line_search_fn != "strong_wolfe":
                    raise ValueError("only 'strong_wolfe' is supported")
                x_init = self._gather_flat_params()

                def obj_func(x, t, d):
                    return self._directional_evaluate(closure, x, t, d)

                fv, flat_grad, t, ls_evals = _strong_wolfe(
                    obj_func, x_init, t, d, fv, flat_grad, gtd,
                    tolerance_change=self._tol_change)
                self._set_flat_params(x_init + t * d)
                current_evals += ls_evals
            else:
                self._set_flat_params(self._gather_flat_params() + t * d)
                if n_iter != self._max_iter:
                    loss = closure()
                    fv = float(loss._value if isinstance(loss, Tensor)
                               else loss)
                    flat_grad = self._gather_flat_grad()
                    current_evals += 1

            if current_evals >= self._max_eval:
                break
            if float(jnp.abs(flat_grad).max()) <= self._tol_grad:
                break
            if float(jnp.abs(d * t).max()) <= self._tol_change:
                break
            # reference's flat-loss criterion: stop when the loss stops
            # moving even though grad/step tolerances haven't triggered
            if prev_fv is not None and abs(fv - prev_fv) < self._tol_change:
                break
            prev_fv = fv

        st["d"], st["t"] = d, t
        st["prev_flat_grad"] = prev_flat_grad
        st["h_diag"] = h_diag
        return orig_loss

    # functional Engine path intentionally unsupported: LBFGS is a
    # closure-driven host-loop algorithm (ref has the same eager-only shape)
    def init_state(self, params):
        raise NotImplementedError(
            "LBFGS is closure-driven (multiple loss evaluations per step) "
            "and runs on the eager path only — use opt.step(closure)")
