"""paddle.hub parity-lite (ref: python/paddle/hapi/hub.py).

`list`/`help`/`load` over LOCAL hubconf.py directories work exactly like
the reference; github/gitee sources are gated (this environment has no
network egress, and TPU deployments typically vendor their model code).
"""
from __future__ import annotations

import importlib.util
import os

__all__ = ["list", "help", "load"]


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no hubconf.py in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _check_source(source):
    if source != "local":
        raise NotImplementedError(
            "paddle_tpu.hub supports source='local' only (no network "
            "egress on TPU pods; vendor the repo and point at its "
            "directory)")


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    """Entrypoints exposed by the repo's hubconf.py."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return getattr(mod, model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"hubconf has no entrypoint {model!r}; "
                         f"available: {list(repo_dir)}")
    return fn(**kwargs)
