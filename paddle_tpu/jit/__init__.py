"""paddle.jit parity (ref: python/paddle/jit/*).

@to_static: the reference rewrites Python AST into a static Program; here
the same contract (trace once, run compiled) is jax.jit. A Layer's forward
becomes a pure function of (state_dict, inputs) via nn.functional_call, so
the compiled artifact is a real program, not a Python closure.

jit.save/load: exports StableHLO via jax.export + the state dict — the
moral equivalent of __model__ + .pdiparams; reloadable and runnable without
the model class.
"""
from __future__ import annotations

import itertools
import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer import Layer, functional_call
from ..observability.trace import get_tracer
from ..tensor import Tensor

__all__ = ["to_static", "save", "load", "InputSpec", "not_to_static",
           "TranslatedLayer", "enable_to_static", "dy2static"]


class InputSpec:
    """ref: paddle.static.InputSpec."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def to_shape_struct(self):
        from .. import framework
        shape = tuple(1 if (s is None or s < 0) else int(s) for s in self.shape)
        return jax.ShapeDtypeStruct(shape, framework.convert_dtype(self.dtype))

    @classmethod
    def from_tensor(cls, t, name=None):
        return cls(tuple(t.shape), str(t.dtype), name)


def _unwrap(x):
    return jax.tree_util.tree_map(
        lambda t: t._value if isinstance(t, Tensor) else t, x,
        is_leaf=lambda t: isinstance(t, Tensor))


_SITE_IDS = itertools.count()


class StaticFunction:
    """Callable wrapper produced by @to_static."""

    def __init__(self, fn, input_spec=None, layer=None, full_graph=True):
        self._fn = fn
        self._orig_fn = fn            # pristine original; _fn may be
        self._layer = layer           # swapped for a dy2static rewrite
        self._input_spec = input_spec
        self._compiled = {}
        self._tracing = False
        self._ast_tried = False
        # unique RecompileTracer site per wrapper: two StaticFunctions
        # over different layers can share input signatures, and a
        # shared site would misread the second one's first trace as an
        # unexpected retrace
        self._site = f"to_static_{next(_SITE_IDS)}"
        self._tracer_sites = set()

    def __del__(self):
        # release this wrapper's sites from the process-global tracer
        # (a site that saw an unexpected retrace is kept — forget()
        # refuses, so churn can't launder the signal); the bare-jax
        # caches previously died with the wrapper, the tracer's
        # accounting must too
        try:
            tracer = get_tracer()
            for site in self._tracer_sites:
                tracer.forget(site)
        except Exception:  # noqa: BLE001 — interpreter shutdown
            pass

    def __call__(self, *args, **kwargs):
        if not _TO_STATIC_ENABLED[0]:
            # enable_to_static(False): run the ORIGINAL eagerly (never a
            # dy2static rewrite — this is the debugging escape hatch)
            return self._orig_fn(*args, **kwargs)
        if self._tracing:
            # re-entered from inside our own trace (a to_static Layer's
            # forward is dispatched through this wrapper): run the
            # captured fn so tracing flows through it
            return self._fn(*args, **kwargs)
        from . import dy2static as _d2s
        try:
            self._tracing = True
            return self._run_compiled(args, kwargs)
        except _d2s._TRACE_ERRORS as e:
            self._tracing = False
            if not self._ast_tried:
                # dy2static fallback (ref: python/paddle/jit/dy2static):
                # lower simple tensor-dependent if/while to lax.cond /
                # lax.while_loop and retry the trace once; on ANY retry
                # failure restore the original so the wrapper is never
                # left pointing at a broken rewrite
                self._ast_tried = True
                new_fn = _d2s.transform_function(self._fn)
                if new_fn is not None:
                    self._fn = new_fn
                    self._compiled.clear()
                    try:
                        return self.__call__(*args, **kwargs)
                    except Exception:
                        self._fn = self._orig_fn
                        self._compiled.clear()
                        raise
            raise _d2s.ControlFlowError(
                _d2s.describe_site(self._orig_fn)) from e
        finally:
            self._tracing = False

    def _run_compiled(self, args, kwargs):
        layer = self._layer
        if layer is not None:
            params, buffers = layer.raw_state()
            training = layer.training

            def pure(p, b, key, *a):
                out, new_b = functional_call(layer, p, b, *a, rng=key,
                                             mutable=True)
                return _unwrap(out), new_b

            jitted = self._compiled.get(("layer", training))
            if jitted is None:
                # through the RecompileTracer: a to_static trace is a
                # compile the zero-recompile report must see (the
                # train/eval split gets its own site — same shapes,
                # different program). introspect=False: no AOT-replay
                # double compile on a user-facing one-shot build.
                site = (f"{self._site}_"
                        f"{'train' if training else 'eval'}")
                jitted = get_tracer().jit(site, pure,
                                          introspect=False)
                self._tracer_sites.add(site)
                self._compiled[("layer", training)] = jitted
            from ..framework import next_rng_key
            arr_args = _unwrap(args)
            out, new_b = jitted(params, buffers, next_rng_key(), *arr_args)
            layer.load_raw_state(buffers=new_b)
            return jax.tree_util.tree_map(Tensor, out)
        jitted = self._compiled.get("fn")
        if jitted is None:
            def pure(*a, **kw):
                return _unwrap(self._fn(*a, **kw))
            site = f"{self._site}_fn"
            jitted = get_tracer().jit(site, pure, introspect=False)
            self._tracer_sites.add(site)
            self._compiled["fn"] = jitted
        out = jitted(*_unwrap(args), **_unwrap(kwargs))
        return jax.tree_util.tree_map(Tensor, out)

    @property
    def forward_fn(self):
        return self._fn

    def concrete_program(self, *args):
        return self


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True, **kwargs):
    def deco(fn):
        if isinstance(fn, Layer):
            layer = fn
            sf = StaticFunction(layer.forward, input_spec, layer=layer)
            layer.forward = sf  # bound replacement; layer(x) now runs jitted
            layer._to_static_spec = input_spec
            return layer
        import functools
        sf = StaticFunction(fn, input_spec)
        functools.update_wrapper(sf, fn)
        return sf

    if function is not None:
        return deco(function)
    return deco


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def save(layer, path, input_spec=None, **configs):
    """Export layer -> {path}.stablehlo + {path}.pdiparams-style state."""
    from jax import export as jax_export

    if input_spec is None:
        input_spec = getattr(layer, "_to_static_spec", None)
    if input_spec is None:
        raise ValueError("jit.save needs input_spec (list of InputSpec)")
    specs = [s if isinstance(s, InputSpec) else InputSpec.from_tensor(s)
             for s in input_spec]
    params, buffers = layer.raw_state()
    was_training = layer.training
    layer.eval()

    def pure(p, b, *a):
        out = functional_call(layer, p, b, *a)
        return _unwrap(out)

    shape_args = [s.to_shape_struct() for s in specs]
    exp = jax_export.export(jax.jit(pure))(
        jax.tree_util.tree_map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params),
        jax.tree_util.tree_map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), buffers),
        *shape_args)
    blob = exp.serialize()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    # atomic tmp-rename (io.atomic): a crash mid-export must leave the
    # previous artifact or none — never a torn .stablehlo a later
    # jit.load would feed to the deserializer
    from ..io.atomic import atomic_replace
    atomic_replace(path + ".stablehlo", blob)
    from ..serialization import save as _save
    _save({"params": {k: Tensor(v) for k, v in params.items()},
           "buffers": {k: Tensor(v) for k, v in buffers.items()},
           "specs": [(s.shape, str(s.dtype)) for s in specs]},
          path + ".pdiparams")
    if was_training:
        layer.train()


class TranslatedLayer(Layer):
    """A reloaded exported program (ref: paddle.jit.TranslatedLayer)."""

    def __init__(self, exported, params, buffers):
        super().__init__()
        self._exported = exported
        self._params = params
        self._buffers_v = buffers

    def forward(self, *args):
        arr_args = _unwrap(args)
        out = self._exported.call(self._params, self._buffers_v, *arr_args)
        return jax.tree_util.tree_map(Tensor, out)

    def train(self):
        raise RuntimeError("TranslatedLayer is inference-only")


def load(path, **configs):
    from jax import export as jax_export
    with open(path + ".stablehlo", "rb") as f:
        exp = jax_export.deserialize(f.read())
    from ..serialization import load as _load
    blob = _load(path + ".pdiparams")
    params = {k: v._value for k, v in blob["params"].items()}
    buffers = {k: v._value for k, v in blob["buffers"].items()}
    return TranslatedLayer(exp, params, buffers)


def get_hlo(layer_or_fn, *example_inputs, stage="stablehlo",
            optimized=False):
    """Program introspection: the traced program's IR as text.

    ref: paddle.static.Program.to_string / print_program — the reference
    dumps its static Program proto; the XLA-native equivalent is the
    lowered StableHLO (or backend-optimized HLO) of the jitted function.

    layer_or_fn: a Layer (traced as functional_call over its state) or any
    jax-traceable callable. example_inputs: Tensors/arrays/InputSpecs.
    stage: "stablehlo" (portable pre-optimization IR) or "hlo".
    optimized=True returns the backend-optimized HLO (after fusion —
    what the R3 fusion audit reads).
    """
    args = [a.to_shape_struct() if isinstance(a, InputSpec)
            else _unwrap(a) for a in example_inputs]
    if isinstance(layer_or_fn, Layer):
        layer = layer_or_fn
        params, buffers = layer.raw_state()

        def fn(p, b, *xs):
            out = functional_call(layer, p, b, *[Tensor(x) for x in xs])
            return _unwrap(out)
        lowered = jax.jit(fn).lower(params, buffers, *args)
    else:
        lowered = jax.jit(layer_or_fn).lower(*args)
    if optimized:
        return lowered.compile().as_text()
    if stage not in ("stablehlo", "hlo"):
        raise ValueError(f"stage must be 'stablehlo' or 'hlo', got {stage!r}")
    return lowered.as_text(dialect=stage)


__all__.append("get_hlo")


_TO_STATIC_ENABLED = [True]


def enable_to_static(flag: bool):
    """ref: paddle.jit.enable_to_static — globally toggle to_static; when
    off, decorated functions run eagerly (debugging parity)."""
    _TO_STATIC_ENABLED[0] = bool(flag)



from . import dy2static  # noqa: E402  (public: paddle.jit.dy2static)
