"""AOT serving artifacts — boot a warmed ServingEngine in seconds.

The autoscaler's reaction time is floored by replica boot, and replica
boot is floored by tracing: every respawn re-traces the full serving
program set (prefill buckets, the decode scan, the spec-verify
program) through Python before the warm-boot gate passes. This module
exports a warmed engine's programs via ``jax.export`` into a
**versioned, fingerprinted, crash-safe artifact**, and restores a
serving-ready engine from one WITHOUT tracing Python — so a scale-out
alert buys capacity in seconds, not compiles (ROADMAP item 3).

Artifact layout (a directory under the store root)::

    <root>/art-<fphash>-<n>/
        manifest.json        # fingerprint + per-blob sha256, atomic
        decode.stablehlo     # jax.export blobs, one per program site
        prefill_64.stablehlo
        ...
        COMPLETE             # written strictly LAST (io.atomic)

Crash-safety is the io.atomic discipline end to end: blobs land in a
``.stage-*`` sibling, every byte is fsynced, the directory is renamed
into place, and the COMPLETE marker is written strictly after — a
crash at ANY point leaves an unmarked (ignored) directory, never a
loadable half-artifact.

Robustness is the headline: the loader re-hashes every blob, diffs the
manifest fingerprint field-by-field against the live engine (model
config, dtype, page geometry, sampling, spec/prefix arming, jax/jaxlib
version, device kind), and on ANY mismatch raises ``ArtifactError``
with a machine-readable reason. ``warm_boot`` counts each fallback in
``serve_aot_fallback_total{reason}`` and falls back to the traced boot
path — never a wrong program, never a silent slow boot.

Token-exactness: the exported blob is the SAME jaxpr the traced boot
would compile (serialized StableHLO of the engine's own program
bodies), primed with the same trash-page synthetic arguments, with the
host RNG untouched — an artifact-booted engine generates
token-for-token what a traced-boot engine does, with zero post-load
Python traces.

Knobs (docs/observability.md): ``PADDLE_TPU_AOT_ARTIFACTS`` (kill
switch), ``PADDLE_TPU_AOT_DIR`` (store root), ``PADDLE_TPU_AOT_TTL_S``
(max artifact age).
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import time

__all__ = ["ArtifactError", "artifact_fingerprint", "export_artifact",
           "load_artifact", "warm_boot"]

#: bump when the manifest/blob layout or the program calling
#: convention changes — a version mismatch is a stale fingerprint
FORMAT_VERSION = 1

_MANIFEST = "manifest.json"
_STAGE_PREFIX = ".stage-"
_ART_PREFIX = "art-"

#: every serving program donates the page pool at argument index 2
#: (the _counting contract); recorded per blob so the loader can't
#: drift from the export
_DONATE_PAGES = (2,)

#: fallback reasons — the serve_aot_fallback_total label vocabulary
REASONS = ("missing", "torn", "bad_manifest", "expired", "wrong_device",
           "stale_fingerprint", "bad_checksum", "deserialize_error",
           "install_error")


class ArtifactError(Exception):
    """A load-blocking artifact fault. `reason` is one of REASONS —
    the serve_aot_fallback_total{reason} label the caller counts."""

    def __init__(self, reason, detail=""):
        self.reason = reason
        self.detail = detail
        super().__init__(f"{reason}: {detail}" if detail else reason)


def _off(val, default="1"):
    return str(val if val is not None else default).lower() \
        in ("0", "false", "off")


def _cfg_dict(cfg):
    """The model config as a stable, JSON-safe dict (primitive fields
    only, sorted) — the model-architecture leg of the fingerprint."""
    out = {}
    for k, v in sorted(vars(cfg).items()):
        if isinstance(v, (str, int, float, bool)) or v is None:
            out[k] = v
    return out


def artifact_fingerprint(engine):
    """Everything that must match for a serialized program to be THE
    program this engine would trace: model architecture + dtype, page
    geometry, sampling, spec/prefix arming, jax/jaxlib version —
    plus the device (compared separately: a platform mismatch is
    `wrong_device`, not `stale_fingerprint`)."""
    import jax
    import jaxlib
    dev = jax.devices()[0]
    spec = engine._spec
    return {
        "format": FORMAT_VERSION,
        "model": type(engine.model).__name__,
        "config": _cfg_dict(engine.cfg),
        "cache_dtype": engine.cache_dtype,
        "page_size": engine.page_size,
        "max_slots": engine.max_slots,
        "max_seq_len": engine.max_seq_len,
        "num_pages": engine.num_pages,
        "steps_per_dispatch": engine.steps_per_dispatch,
        "pad_token_id": engine.pad_token_id,
        "use_flash": bool(engine.use_flash),
        "donate": bool(engine.donate),
        "sampling": {"temperature": engine.temperature,
                     "top_k": engine.top_k,
                     "seed": engine.sampling_seed},
        "prefix": {"on": engine.prefix is not None,
                   "min_pages": None if engine.prefix is None
                   else engine.prefix.min_pages},
        "spec": {"armed": spec is not None,
                 "k": engine.spec_k if spec is not None else None,
                 "draft": spec.kind if spec is not None else None},
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "device": {"platform": dev.platform,
                   "kind": getattr(dev, "device_kind", dev.platform)},
    }


def _fp_hash(fp):
    blob = json.dumps(fp, sort_keys=True, allow_nan=False)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def _sites(engine):
    """The warmed program set, in install order."""
    out = [f"prefill_{n}" for n in sorted(engine._warmed_buckets)]
    out += [f"tail_prefill_{t}"
            for t in sorted(engine._warmed_tail_buckets)]
    if engine._warmed_decode:
        out.append("decode")
    if engine._warmed_spec:
        out.append("spec_verify")
    return out


def _candidates(root):
    """Marked artifact dirs under `root`, newest manifest first."""
    from ..io.atomic import has_marker
    found, unmarked = [], 0
    try:
        entries = sorted(os.listdir(root))
    except OSError:
        return [], 0
    for name in entries:
        path = os.path.join(root, name)
        if not (name.startswith(_ART_PREFIX) and os.path.isdir(path)):
            continue
        if not has_marker(path):
            unmarked += 1       # a torn (crashed-mid-export) artifact
            continue
        try:
            with open(os.path.join(path, _MANIFEST)) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError, ValueError):
            found.append((0.0, path, None))     # marked but unreadable
            continue
        found.append((float(manifest.get("created_at") or 0.0),
                      path, manifest))
    found.sort(key=lambda x: (-x[0], x[1]))
    return found, unmarked


# -- export ------------------------------------------------------------------

def export_artifact(engine, root, prune=True):
    """Serialize the warmed engine's full program set into a fresh
    crash-safe artifact under `root`. Returns the artifact dir, or the
    existing one when an artifact with this exact fingerprint and a
    superset of the warmed sites is already published (idempotent —
    a fleet of replicas sharing a store exports once).

    Every program body is AOT-lowered via jax.export from the same raw
    fn + jit kwargs the traced boot compiles (engine._aot_programs),
    with the same warm-arg signatures — so the artifact IS the traced
    program, serialized. Staging + publish follow io.atomic: blobs are
    atomically written into a .stage sibling, fsynced, dir-renamed,
    marker strictly last (publish_dir)."""
    import jax
    from jax import export as jax_export
    from ..io.atomic import atomic_replace, publish_dir
    if not engine.warmed:
        raise RuntimeError("export_artifact needs a warmed engine — "
                           "warmup() first (export is a boot step)")
    fp = artifact_fingerprint(engine)
    fph = _fp_hash(fp)
    sites = _sites(engine)
    os.makedirs(root, exist_ok=True)
    cands, _ = _candidates(root)
    for _ts, path, manifest in cands:
        if manifest and manifest.get("fingerprint") == fp \
                and set(sites) <= set(manifest.get("blobs") or ()):
            return path
    staging = os.path.join(
        root, f"{_STAGE_PREFIX}{os.getpid()}-{fph}-{time.time_ns()}")
    os.makedirs(staging)
    blobs = {}
    for site in sites:
        fn, kw = engine._aot_programs[site]
        args = engine._warm_args(site)
        # one-shot AOT lowering of the raw program body — traced here,
        # at export time, never dispatched (the tracer-wrapped twin is
        # what serves); see tpulint baseline justification
        exp = jax_export.export(jax.jit(fn, **kw))(*args)
        blob = exp.serialize()
        fname = f"{site}.stablehlo"
        atomic_replace(os.path.join(staging, fname), blob, fsync=False)
        blobs[site] = {
            "file": fname,
            "bytes": len(blob),
            "sha256": hashlib.sha256(blob).hexdigest(),
            "donate_argnums": list(_DONATE_PAGES) if engine.donate
            else [],
        }
    manifest = {
        "version": FORMAT_VERSION,
        "created_at": time.time(),
        "fingerprint": fp,
        "warmed": {"buckets": sorted(engine._warmed_buckets),
                   "tail_buckets": sorted(engine._warmed_tail_buckets),
                   "decode": engine._warmed_decode,
                   "spec": engine._warmed_spec},
        "blobs": blobs,
    }
    atomic_replace(os.path.join(staging, _MANIFEST),
                   json.dumps(manifest, sort_keys=True, indent=1,
                              allow_nan=False),
                   fsync=False)
    final = os.path.join(root, f"{_ART_PREFIX}{fph}-{time.time_ns()}")
    publish_dir(staging, final)
    from ..observability import flightrec
    flightrec.note("serve_aot_export", artifact=os.path.basename(final),
                   sites=sites, fingerprint_hash=fph)
    if prune:
        _prune(root, keep=final)
    return final


def _prune(root, keep, stage_ttl_s=86400.0):
    """Store hygiene, best-effort: drop superseded MARKED artifacts
    (the loader only ever reads the newest) and stage leftovers older
    than `stage_ttl_s` (a concurrent exporter's live staging dir is
    younger and survives)."""
    from ..io.atomic import has_marker
    now = time.time()
    try:
        entries = sorted(os.listdir(root))
    except OSError:
        return
    for name in entries:
        path = os.path.join(root, name)
        try:
            if name.startswith(_STAGE_PREFIX):
                if now - os.path.getmtime(path) > stage_ttl_s:
                    shutil.rmtree(path, ignore_errors=True)
            elif name.startswith(_ART_PREFIX) and os.path.isdir(path) \
                    and path != keep and has_marker(path):
                shutil.rmtree(path, ignore_errors=True)
        except OSError:
            continue


# -- load --------------------------------------------------------------------

def _diff_fingerprint(want, got):
    """Top-level fingerprint fields that disagree (sorted)."""
    keys = set(want) | set(got if isinstance(got, dict) else {})
    keys.discard("device")
    return sorted(k for k in keys
                  if (got or {}).get(k) != want.get(k))


def load_artifact(engine, root, ttl_s=None, buckets=()):
    """Restore a serving-ready, warmed engine from the newest artifact
    under `root` WITHOUT tracing Python: every blob is re-hashed
    against the manifest, the fingerprint is diffed field-by-field
    against the live engine, and only then are the deserialized
    programs installed, primed once with the same trash-page synthetic
    arguments warmup() uses, and the _warmed_* flags flipped.

    Raises ArtifactError(reason) on ANY fault — the engine is left
    exactly as found (installation is all-or-nothing: deserialization
    and platform checks happen before the first install; an install-
    time fault rolls the program table back to build-on-first-use).
    Returns a boot-info dict (artifact name, sites, topped-up
    buckets)."""
    import jax
    from jax import export as jax_export
    if engine._state == "closed":
        raise RuntimeError("ServingEngine is closed")
    if not os.path.isdir(root):
        raise ArtifactError("missing", f"no artifact store at {root}")
    cands, unmarked = _candidates(root)
    if not cands:
        if unmarked:
            raise ArtifactError(
                "torn", f"{unmarked} unmarked artifact dir(s) under "
                        f"{root} (crash mid-export) and no complete one")
        raise ArtifactError("missing", f"no published artifact in {root}")
    created, path, manifest = cands[0]
    name = os.path.basename(path)
    if manifest is None:
        raise ArtifactError("bad_manifest",
                            f"{name}: unreadable manifest.json")
    if manifest.get("version") != FORMAT_VERSION:
        raise ArtifactError(
            "stale_fingerprint",
            f"{name}: format v{manifest.get('version')} != "
            f"v{FORMAT_VERSION}")
    if ttl_s is not None and time.time() - created > float(ttl_s):
        raise ArtifactError(
            "expired", f"{name}: {time.time() - created:.0f}s old "
                       f"> ttl {float(ttl_s):.0f}s")
    want = artifact_fingerprint(engine)
    got = manifest.get("fingerprint") or {}
    if got.get("device") != want["device"]:
        raise ArtifactError(
            "wrong_device",
            f"{name}: built for {got.get('device')}, "
            f"running on {want['device']}")
    bad = _diff_fingerprint(want, got)
    if bad:
        raise ArtifactError(
            "stale_fingerprint", f"{name}: mismatched {', '.join(bad)}")

    # verify + deserialize EVERY blob before touching the engine
    blobs = manifest.get("blobs") or {}
    platform = jax.devices()[0].platform
    exps = {}
    blob_bytes = 0
    for site, meta in sorted(blobs.items()):
        bpath = os.path.join(path, meta.get("file") or "")
        try:
            with open(bpath, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise ArtifactError("torn",
                                f"{name}: blob {site} unreadable "
                                f"({e})") from e
        digest = hashlib.sha256(raw).hexdigest()
        if digest != meta.get("sha256"):
            raise ArtifactError(
                "bad_checksum",
                f"{name}: blob {site} sha256 {digest[:12]}… != "
                f"manifest {str(meta.get('sha256'))[:12]}…")
        try:
            exp = jax_export.deserialize(raw)
        except Exception as e:  # noqa: BLE001 — any decode fault
            raise ArtifactError(
                "deserialize_error", f"{name}: blob {site}: {e}") from e
        if platform not in exp.platforms:
            raise ArtifactError(
                "wrong_device",
                f"{name}: blob {site} lowered for {exp.platforms}, "
                f"running on {platform}")
        exps[site] = (exp, tuple(meta.get("donate_argnums") or ()))
        blob_bytes += len(raw)

    warmed = manifest.get("warmed") or {}
    try:
        for site, (exp, donate) in sorted(exps.items()):
            kw = {"donate_argnums": donate} \
                if (engine.donate and donate) else {}
            # through the engine's own RecompileTracer, so the one
            # wrapper trace of exp.call (NOT of the Python model)
            # lands in compile_counts like any boot compile, and a
            # steady-state retrace would still trip the
            # zero-recompile accounting. introspect=False: no
            # AOT-replay double compile at boot.
            call = engine.tracer.jit(site, exp.call, introspect=False,
                                     **kw)
            engine._install_aot_program(site, call)
            engine._prime(site, call)
        engine._warmed_buckets.update(warmed.get("buckets") or ())
        engine._warmed_tail_buckets.update(
            warmed.get("tail_buckets") or ())
        engine._warmed_decode |= bool(warmed.get("decode"))
        if engine._spec is not None and warmed.get("spec"):
            engine._warmed_spec = True
        norm = sorted(engine._warmed_buckets)
        if engine.prefix is not None and norm:
            engine._warm_eager_ladder(norm)
        if engine._spec is not None:
            # the proposer's own programs (draft prefill/propose scan
            # for a model draft; nothing for ngram) are tiny — they
            # warm live at load, inside the boot budget
            engine._spec.warmup(engine, norm)
        # traced top-up for anything the caller asked for that the
        # artifact doesn't carry (e.g. a new bucket after a routing
        # change) — loud in compile_counts, never a wrong program
        missing = sorted({engine._bucket_for(n) for n in buckets}
                         - engine._warmed_buckets)
        if missing or not engine._warmed_decode:
            engine.warmup(buckets=missing)
    except ArtifactError:
        raise
    except Exception as e:  # noqa: BLE001 — any install/prime fault
        # roll the program table back to build-on-first-use so a
        # half-installed set can never serve
        engine._decode_fn = engine._build_decode_fn()
        engine._prefill_fns.clear()
        engine._tail_prefill_fns.clear()
        if engine._spec is not None:
            engine._spec_verify_fn = engine._build_spec_verify_fn()
        engine._warmed_buckets.clear()
        engine._warmed_tail_buckets.clear()
        engine._warmed_decode = False
        engine._warmed_spec = False
        raise ArtifactError("install_error", str(e)) from e
    info = {"artifact": name, "sites": sorted(exps),
            "topped_up": missing}
    if getattr(engine, "ledger", None) is not None:
        # artifact restore seam: the deserialized executables' blob
        # bytes land in the ledger's "other" segment (level, not a
        # tracked token — a reload replaces, never accumulates)
        engine.ledger.set_level("other", blob_bytes,
                                label="serving_artifact")
    from ..observability import flightrec
    flightrec.note("serve_aot_load", **info)
    return info


# -- the spawn-path boot ladder ----------------------------------------------

def _own_counter(engine, name, help, labels=None):
    m = engine.registry.counter(
        name, help=help, **({"labels": labels} if labels else {}))
    if m not in engine._own_series:
        engine._own_series.append(m)
    return m


def warm_boot(engine, buckets=(), artifact_dir=None, export=None,
              ttl_s=None):
    """THE fleet spawn path: prefer-artifact, fall back loudly, export
    after a traced boot so the NEXT spawn is fast.

    1. resolve the store root (`artifact_dir`, else PADDLE_TPU_AOT_DIR)
       and the kill switch (PADDLE_TPU_AOT_ARTIFACTS, default on); no
       root or switched off -> plain traced warmup, byte-identical to
       the pre-artifact boot path;
    2. try load_artifact: success is an AOT boot (zero Python traces);
    3. ANY ArtifactError increments
       serve_aot_fallback_total{reason} — the loud part — and falls
       back to traced warmup: never a wrong program, never a silent
       slow boot;
    4. after a traced boot (fallback or cold store), export the warmed
       program set (best-effort, counted on failure) so respawns and
       scale-outs board the fast path.

    Stamps engine.boot_info (mode aot|traced, boot_s, artifact) —
    heartbeats carry it to the supervisor/autoscaler and fleet_top's
    BOOT column. Returns the boot_info dict."""
    t0 = time.monotonic()
    root = artifact_dir if artifact_dir is not None \
        else os.environ.get("PADDLE_TPU_AOT_DIR")
    enabled = root and not _off(
        os.environ.get("PADDLE_TPU_AOT_ARTIFACTS"))
    if ttl_s is None:
        env_ttl = os.environ.get("PADDLE_TPU_AOT_TTL_S")
        ttl_s = float(env_ttl) if env_ttl else None
    if not enabled:
        engine.warmup(buckets=buckets)
        engine.boot_info.update(
            mode="traced", boot_s=round(time.monotonic() - t0, 6),
            artifact=None)
        return dict(engine.boot_info)
    mode, artifact = "traced", None
    try:
        info = load_artifact(engine, root, ttl_s=ttl_s,
                             buckets=buckets)
        mode, artifact = "aot", info["artifact"]
        _own_counter(engine, "serve_aot_loads_total",
                     help="successful artifact boots").inc()
    except ArtifactError as e:
        _own_counter(engine, "serve_aot_fallback_total",
                     help="artifact-boot attempts that fell back to "
                          "the traced path, by reason (torn/stale/"
                          "corrupt artifacts are counted here, never "
                          "silently slow)",
                     labels={"reason": e.reason}).inc()
        from ..observability import flightrec
        flightrec.note("serve_aot_fallback", reason=e.reason,
                       detail=e.detail)
        engine.warmup(buckets=buckets)
        if export is None or export:
            try:
                artifact = os.path.basename(
                    export_artifact(engine, root))
            except Exception as ex:  # noqa: BLE001 — export is an
                #                      optimization; boot must survive
                _own_counter(
                    engine, "serve_aot_export_failures_total",
                    help="artifact exports that failed (boot "
                         "unaffected; the next spawn re-traces)").inc()
                flightrec.note("serve_aot_export_failed",
                               error=str(ex))
    engine.boot_info.update(
        mode=mode, boot_s=round(time.monotonic() - t0, 6),
        artifact=artifact)
    return dict(engine.boot_info)
