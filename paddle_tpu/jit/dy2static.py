"""dy2static: tensor-dependent Python control flow under @to_static.

ref parity: python/paddle/jit/dy2static/ — the reference AST-rewrites
data-dependent Python `if`/`while` into cond/while_loop ops inside its
static Program. The TPU-native substrate is `jax.jit` tracing, where
tensor-dependent Python branching raises a tracer-concretization error.
This module gives that failure a Paddle-voiced story in two stages:

1. AST fallback: when a traced forward hits a concretization error,
   `transform_function` rewrites simple `if`/`while` statements (no
   return/break/continue inside) into `convert_ifelse` /
   `convert_while_loop` calls that lower to `lax.cond` /
   `lax.while_loop` when the predicate is a tracer — and the trace is
   retried once. Plain `and`/`or`/`not` inside the tested condition are
   mapped to `logical_and`/`logical_or`/`logical_not`.
2. Actionable error: anything the transform can't lower re-raises as
   `ControlFlowError` naming the function and source location with the
   lax.cond / lax.while_loop / jnp.where migration recipe (instead of a
   raw JAX TracerBoolConversionError).

The convert_* operators are also public API, mirroring the reference's
convert_operators module, so users can call them directly.
"""
from __future__ import annotations

import ast
import inspect
import textwrap
import types

import jax
import jax.numpy as jnp

__all__ = ["convert_ifelse", "convert_while_loop", "convert_logical_and",
           "convert_logical_or", "convert_logical_not", "ControlFlowError",
           "transform_function", "UNDEFINED"]


class _Undefined:
    """Sentinel for a name with no binding before a converted branch."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<UNDEFINED>"


UNDEFINED = _Undefined()

# static pytree node: lax.cond/while_loop carries treat UNDEFINED as
# structure, so "assigned in neither branch yet" round-trips for free,
# while "assigned in only ONE branch" surfaces as a treedef mismatch we
# convert into an actionable ControlFlowError
jax.tree_util.register_pytree_node(
    _Undefined, lambda u: ((), None), lambda aux, ch: UNDEFINED)

_RECIPE = """\
Tensor-dependent Python control flow cannot be traced into one XLA
program. Rewrite the data-dependent branch with compiled control flow:
  - value select:     y = paddle.where(cond, a, b)          (jnp.where)
  - if/else blocks:   jax.lax.cond(pred, true_fn, false_fn, operand)
  - while loops:      jax.lax.while_loop(cond_fn, body_fn, init)
  - bounded for:      jax.lax.fori_loop / jax.lax.scan
or hoist the condition to a Python value (config flag, shape, .item()
outside the jitted region). paddle_tpu auto-lowers simple if/while
statements; this one could not be lowered (returns/breaks inside a
tensor-dependent branch, or mismatched variables across branches)."""


class ControlFlowError(RuntimeError):
    """Raised when @to_static meets un-lowerable data-dependent control
    flow (ref: dy2static's transformation errors, same role)."""

    def __init__(self, where, detail=""):
        msg = f"to_static: data-dependent control flow in {where}"
        if detail:
            msg += f"\n{detail}"
        super().__init__(msg + "\n" + _RECIPE)


def _raw(x):
    from ..tensor import Tensor
    return x._value if isinstance(x, Tensor) else x


def _is_tracer(x):
    x = _raw(x)
    return isinstance(x, jax.core.Tracer)


def _canon(tree):
    """Uniform carry representation across branches/iterations: one
    branch may bind a variable to a Tensor (layer output) and the other
    to a raw jnp array (arithmetic on a traced input), and a Tensor's
    stop_gradient flag lives in its pytree aux — either way the two
    branch treedefs mismatch under lax.cond. Canonical form: raw arrays,
    with stop_gradient=True materialized as in-graph lax.stop_gradient
    (the semantics move into the program, the structure is uniform).
    Code after a converted block therefore sees jnp arrays, which share
    the Tensor method surface that is legal under tracing."""
    from ..tensor import Tensor

    def leaf(v):
        if isinstance(v, Tensor):
            val = v._value
            if v.stop_gradient and isinstance(val, jax.core.Tracer):
                val = jax.lax.stop_gradient(val)
            return val
        return v
    return jax.tree_util.tree_map(
        leaf, tree, is_leaf=lambda t: isinstance(t, Tensor))


def convert_ifelse(pred, true_fn, false_fn, init):
    """`if pred: ... else: ...` over carried values `init` (tuple).

    Tracer pred -> lax.cond (both branches traced); Python pred -> only
    the taken branch runs. ref: convert_operators.convert_ifelse."""
    pred = _raw(pred)
    if not _is_tracer(pred):
        return true_fn(init) if pred else false_fn(init)
    try:
        return jax.lax.cond(jnp.asarray(pred).reshape(()),
                            lambda c: _canon(true_fn(c)),
                            lambda c: _canon(false_fn(c)), _canon(init))
    except TypeError as e:
        raise ControlFlowError(
            "a converted `if` statement",
            "the two branches produce different variables or dtypes "
            f"(both must bind the same tensors): {e}") from e


def convert_while_loop(cond_fn, body_fn, init):
    """`while cond: ...` over carried values `init` (tuple).

    Tracer condition -> lax.while_loop (carry shapes fixed); Python
    condition -> ordinary loop. ref: convert_operators.convert_while_loop."""
    first = cond_fn(init)
    if not _is_tracer(first):
        carry = init
        cond = first
        while cond:
            carry = body_fn(carry)
            cond = cond_fn(carry)
        return carry
    try:
        return jax.lax.while_loop(
            lambda c: jnp.asarray(_raw(cond_fn(c))).reshape(()),
            lambda c: _canon(body_fn(c)), _canon(init))
    except TypeError as e:
        raise ControlFlowError(
            "a converted `while` loop",
            "the loop body changes the shape/dtype/variables of the "
            f"carried state (it must stay fixed): {e}") from e


def convert_logical_and(lhs_fn, rhs_fn):
    lhs = lhs_fn()
    if _is_tracer(lhs):
        return jnp.logical_and(jnp.asarray(_raw(lhs)),
                               jnp.asarray(_raw(rhs_fn())))
    return lhs and rhs_fn()          # Python short-circuit preserved


def convert_logical_or(lhs_fn, rhs_fn):
    lhs = lhs_fn()
    if _is_tracer(lhs):
        return jnp.logical_or(jnp.asarray(_raw(lhs)),
                              jnp.asarray(_raw(rhs_fn())))
    return lhs or rhs_fn()


def convert_logical_not(x):
    if _is_tracer(x):
        return jnp.logical_not(jnp.asarray(_raw(x)))
    return not x


def _init_carry(local_vars, names):
    return tuple(local_vars.get(n, UNDEFINED) for n in names)


# ---------------------------------------------------------------------
# AST transformation
# ---------------------------------------------------------------------

class _Escape(ast.NodeVisitor):
    """Does a statement list contain return/break/continue/raise at this
    control level (not inside a nested function/loop)? Such a block can't
    become a lax.cond branch: both branches are traced unconditionally,
    so a data-dependent `raise` would fire at trace time for every
    input, and returns/breaks change control flow outside the block."""

    def __init__(self):
        self.found = False

    def visit_Return(self, node):
        self.found = True

    def visit_Break(self, node):
        self.found = True

    def visit_Continue(self, node):
        self.found = True

    def visit_Raise(self, node):
        self.found = True

    def visit_Assert(self, node):
        self.found = True            # assert lowers to a conditional raise

    def visit_FunctionDef(self, node):
        pass                          # nested defs own their returns

    def visit_AsyncFunctionDef(self, node):
        pass

    def visit_Lambda(self, node):
        pass


def _has_escape(stmts):
    v = _Escape()
    for s in stmts:
        v.visit(s)
    return v.found


class _AssignedNames(ast.NodeVisitor):
    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.names.add(node.id)

    def visit_FunctionDef(self, node):
        self.names.add(node.name)

    def visit_AsyncFunctionDef(self, node):
        self.names.add(node.name)

    def visit_Lambda(self, node):
        pass


def _assigned(nodes):
    v = _AssignedNames()
    for n in nodes:
        v.visit(n)
    return v.names


class _LoadedNames(ast.NodeVisitor):
    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.names.add(node.id)


def _loaded(node):
    v = _LoadedNames()
    v.visit(node)
    return v.names


class _BoolOpInTest(ast.NodeTransformer):
    """`a and b` / `a or b` / `not a` inside a tested condition ->
    convert_logical_* (tracer-aware, short-circuit kept for Python
    values via lambdas)."""

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = ("__ptu_and" if isinstance(node.op, ast.And) else "__ptu_or")
        expr = node.values[0]
        for v in node.values[1:]:
            expr = ast.Call(
                func=ast.Name(id=fn, ctx=ast.Load()),
                args=[ast.Lambda(args=_empty_args(), body=expr),
                      ast.Lambda(args=_empty_args(), body=v)],
                keywords=[])
        return expr

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(func=ast.Name(id="__ptu_not", ctx=ast.Load()),
                            args=[node.operand], keywords=[])
        return node


def _empty_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


class _CtrlFlow(ast.NodeTransformer):
    """Rewrite simple If/While into convert_ifelse/convert_while_loop."""

    def __init__(self, fn_locals):
        self.fn_locals = fn_locals    # names local to the function
        self.changed = False
        self._n = 0

    # -- helpers -------------------------------------------------------
    def _carry_tuple(self, names, ctx):
        return ast.Tuple(
            elts=[ast.Name(id=n, ctx=ctx()) for n in names], ctx=ctx())

    def _branch_def(self, fname, names, body):
        """def fname(vals): (names) = vals; <body>; return (names)"""
        stmts = []
        if names:
            stmts.append(ast.Assign(
                targets=[self._carry_tuple(names, ast.Store)],
                value=ast.Name(id="__ptu_vals", ctx=ast.Load())))
        stmts.extend(body)
        stmts.append(ast.Return(value=self._carry_tuple(names, ast.Load)))
        return ast.FunctionDef(
            name=fname,
            args=ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg="__ptu_vals")],
                vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                defaults=[]),
            body=stmts, decorator_list=[], returns=None)

    def _init_call(self, names):
        return ast.Call(
            func=ast.Name(id="__ptu_init", ctx=ast.Load()),
            args=[ast.Call(func=ast.Name(id="locals", ctx=ast.Load()),
                           args=[], keywords=[]),
                  ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                            ctx=ast.Load())],
            keywords=[])

    # -- If ------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        if _has_escape(node.body) or _has_escape(node.orelse):
            return node               # can't lower; runtime error speaks
        names = sorted((_assigned(node.body) | _assigned(node.orelse))
                       & self.fn_locals)
        self._n += 1
        self.changed = True
        tname, fname = f"__ptu_true_{self._n}", f"__ptu_false_{self._n}"
        test = _BoolOpInTest().visit(node.test)
        out = [
            self._branch_def(tname, names, node.body),
            self._branch_def(fname, names,
                             node.orelse or [ast.Pass()]),
            ast.Assign(
                targets=[self._carry_tuple(names, ast.Store)]
                if names else
                [ast.Name(id=f"__ptu_void_{self._n}", ctx=ast.Store())],
                value=ast.Call(
                    func=ast.Name(id="__ptu_ifelse", ctx=ast.Load()),
                    args=[test,
                          ast.Name(id=tname, ctx=ast.Load()),
                          ast.Name(id=fname, ctx=ast.Load()),
                          self._init_call(names)],
                    keywords=[])),
        ]
        return out

    # -- While ---------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if _has_escape(node.body) or node.orelse:
            return node
        names = sorted((_assigned(node.body) | _loaded(node.test))
                       & self.fn_locals)
        self._n += 1
        self.changed = True
        cname, bname = f"__ptu_cond_{self._n}", f"__ptu_body_{self._n}"
        test = _BoolOpInTest().visit(node.test)
        cond_def = self._branch_def(cname, names, [])
        cond_def.body[-1] = ast.Return(value=test)
        body_def = self._branch_def(bname, names, node.body)
        out = [
            cond_def,
            body_def,
            ast.Assign(
                targets=[self._carry_tuple(names, ast.Store)]
                if names else
                [ast.Name(id=f"__ptu_void_{self._n}", ctx=ast.Store())],
                value=ast.Call(
                    func=ast.Name(id="__ptu_while", ctx=ast.Load()),
                    args=[ast.Name(id=cname, ctx=ast.Load()),
                          ast.Name(id=bname, ctx=ast.Load()),
                          self._init_call(names)],
                    keywords=[])),
        ]
        return out


def _function_locals(fn_node):
    names = {a.arg for a in fn_node.args.args}
    names |= {a.arg for a in fn_node.args.posonlyargs}
    names |= {a.arg for a in fn_node.args.kwonlyargs}
    if fn_node.args.vararg:
        names.add(fn_node.args.vararg.arg)
    if fn_node.args.kwarg:
        names.add(fn_node.args.kwarg.arg)
    names |= _assigned(fn_node.body)
    return names


def _is_to_static_deco(node):
    """Match @to_static / @paddle.jit.to_static(...) decorators so only
    they are stripped from the recompiled function."""
    target = node.func if isinstance(node, ast.Call) else node
    if isinstance(target, ast.Attribute):
        return target.attr == "to_static"
    return isinstance(target, ast.Name) and target.id == "to_static"


class _ZeroArgSuper(ast.NodeTransformer):
    """`super()` relies on the compiler-provided __class__ cell, which an
    exec-compiled module-level def doesn't have — rewrite to the two-arg
    form using the original closure's __class__ and the first param."""

    def __init__(self, self_name):
        self.self_name = self_name

    def visit_Call(self, node):
        self.generic_visit(node)
        if (isinstance(node.func, ast.Name) and node.func.id == "super"
                and not node.args and not node.keywords):
            node.args = [ast.Name(id="__ptu_class__", ctx=ast.Load()),
                         ast.Name(id=self.self_name, ctx=ast.Load())]
        return node


def transform_function(fn):
    """AST-rewrite `fn` lowering simple if/while to converted control
    flow. Returns the new function, or None if nothing was (or could
    be) rewritten. Bound methods come back re-bound.

    Limitation: closure variables are snapshotted at transform time; a
    free variable rebound later in the enclosing scope keeps its
    transform-time value inside the rewritten function."""
    bound_self = getattr(fn, "__self__", None)
    raw_fn = fn.__func__ if bound_self is not None else fn
    try:
        src = textwrap.dedent(inspect.getsource(raw_fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return None
    fn_node = tree.body[0]
    if not isinstance(fn_node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    fn_node.decorator_list = [d for d in fn_node.decorator_list
                              if not _is_to_static_deco(d)]
    closure_vars = {}
    if raw_fn.__closure__:
        try:
            closure_vars = {
                n: c.cell_contents for n, c in
                zip(raw_fn.__code__.co_freevars, raw_fn.__closure__)}
        except ValueError:            # an unfilled cell — can't snapshot
            return None
    if "super" in _loaded(fn_node):
        cls_cell = closure_vars.get("__class__")
        if cls_cell is None or not fn_node.args.args:
            return None               # zero-arg super() unrewritable
        closure_vars["__ptu_class__"] = cls_cell
        fn_node = _ZeroArgSuper(fn_node.args.args[0].arg).visit(fn_node)
    tr = _CtrlFlow(_function_locals(fn_node))
    new_node = tr.visit(fn_node)
    if not tr.changed:
        return None
    mod = ast.Module(body=[new_node], type_ignores=[])
    ast.fix_missing_locations(mod)
    glb = dict(raw_fn.__globals__)
    glb.update(closure_vars)
    glb.update({
        "__ptu_ifelse": convert_ifelse,
        "__ptu_while": convert_while_loop,
        "__ptu_and": convert_logical_and,
        "__ptu_or": convert_logical_or,
        "__ptu_not": convert_logical_not,
        "__ptu_init": _init_carry,
    })
    code = compile(mod, filename=f"<dy2static {raw_fn.__qualname__}>",
                   mode="exec")
    ns = {}
    exec(code, glb, ns)
    new_fn = ns[fn_node.name]
    new_fn.__dy2static__ = True
    if bound_self is not None:
        return types.MethodType(new_fn, bound_self)
    return new_fn


_TRACE_ERRORS = (jax.errors.ConcretizationTypeError,
                 jax.errors.TracerBoolConversionError,
                 jax.errors.TracerIntegerConversionError,
                 jax.errors.TracerArrayConversionError)


def describe_site(fn):
    """'forward of MyNet (file.py:42)' for error messages."""
    raw = getattr(fn, "__func__", fn)
    try:
        file = inspect.getsourcefile(raw)
        _, line = inspect.getsourcelines(raw)
        return f"{raw.__qualname__} ({file}:{line})"
    except (OSError, TypeError):
        return getattr(raw, "__qualname__", repr(raw))
