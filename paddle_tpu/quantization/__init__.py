"""paddle.quantization parity (ref: python/paddle/quantization/).

TPU-native quantization-aware training and post-training quantization:

- fake-quant runs INSIDE the jitted train step as pure ops with a
  straight-through estimator (jnp.round has zero gradient; the STE is the
  `x + stop_gradient(q - x)` identity), so QAT costs one fused
  multiply-round-clip per quantized tensor — no custom kernels needed;
- observers are functional: they fold the running absmax into the layer's
  buffer dict, so calibration (PTQ) is just forward passes under the
  normal Engine/eager machinery;
- `convert` produces an inference model whose weights are materialized
  int8 with per-channel scales — int8 matmuls lower onto the v5e int8
  MXU path (394 TOPS) via lax.dot_general preferred_element_type.
"""
from .config import QuantConfig  # noqa: F401
from .observers import AbsmaxObserver, EMAObserver  # noqa: F401
from .quanters import (  # noqa: F401
    FakeQuanterWithAbsMax, FakeQuanterChannelWiseAbsMax, quant_dequant,
)
from .qat import QAT  # noqa: F401
from .ptq import PTQ  # noqa: F401
from .layers import Int8InferLinear, QuantedConv2D, QuantedLinear  # noqa: F401

__all__ = [
    "QuantConfig", "AbsmaxObserver", "EMAObserver",
    "FakeQuanterWithAbsMax", "FakeQuanterChannelWiseAbsMax",
    "quant_dequant", "QAT", "PTQ", "QuantedLinear", "QuantedConv2D",
    "Int8InferLinear",
]
