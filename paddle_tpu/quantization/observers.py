"""Calibration observers (ref: python/paddle/quantization/observers/).

Observers watch activations during PTQ calibration forwards and expose the
resulting scale. State lives in buffers so calibration works through the
same functional machinery as training.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..autograd import apply_op
from ..nn.layer import Layer
from ..tensor import Tensor, to_tensor

__all__ = ["AbsmaxObserver", "EMAObserver"]


class AbsmaxObserver(Layer):
    """ref: AbsmaxObserver — running max of |x| over calibration batches."""

    def __init__(self, bit_length=8, name=None):
        super().__init__()
        self.bit_length = bit_length
        self._frozen = False  # convert() sets this: calibration ends there
        self.register_buffer("scale", to_tensor(jnp.zeros((), jnp.float32)))

    def forward(self, x):
        if self._frozen:
            return x
        t = x if isinstance(x, Tensor) else to_tensor(x)
        cur = apply_op(lambda a: jnp.max(jnp.abs(a)).astype(jnp.float32),
                       t, differentiable=False)
        # in-place buffer value update (see quanters.py note)
        self.scale._value = jnp.maximum(self.scale._value, cur._value)
        return x

    def quant_axis(self):
        return None


class EMAObserver(Layer):
    """ref: EMDObserver-family — exponential moving average of absmax."""

    def __init__(self, bit_length=8, moving_rate=0.9, name=None):
        super().__init__()
        self.bit_length = bit_length
        self.moving_rate = moving_rate
        self._frozen = False  # convert() sets this: calibration ends there
        self.register_buffer("scale", to_tensor(jnp.zeros((), jnp.float32)))

    def forward(self, x):
        if self._frozen:
            return x
        t = x if isinstance(x, Tensor) else to_tensor(x)
        cur = apply_op(lambda a: jnp.max(jnp.abs(a)).astype(jnp.float32),
                       t, differentiable=False)
        r = self.moving_rate
        s = self.scale._value
        self.scale._value = jnp.where(s > 0, r * s + (1 - r) * cur._value,
                                      cur._value)
        return x

    def quant_axis(self):
        return None
