"""QAT driver (ref: python/paddle/quantization/qat.py).

`QAT(config).quantize(model)` swaps Linear/Conv2D sublayers for quant
wrappers in place (returns the same model object, like the reference's
in-place=True default); `convert(model)` materializes int8 inference
layers from the learned scales.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..nn.layer import Layer
from ..nn.layers_common import Linear
from ..nn.layers_conv import Conv2D
from .config import QuantConfig
from .layers import Int8InferLinear, QuantedConv2D, QuantedLinear
from .quanters import FakeQuanterChannelWiseAbsMax, FakeQuanterWithAbsMax

__all__ = ["QAT"]

_WRAP = {Linear: QuantedLinear, Conv2D: QuantedConv2D}


def _default_config():
    cfg = QuantConfig(
        activation=lambda: FakeQuanterWithAbsMax(8),
        weight=lambda: FakeQuanterChannelWiseAbsMax(
            8, channel_axis=1))  # Linear weight [in, out]: per-out-feature
    cfg.add_type_config(
        Conv2D,
        activation=lambda: FakeQuanterWithAbsMax(8),
        weight=lambda: FakeQuanterChannelWiseAbsMax(8, channel_axis=0))
    return cfg


class QAT:
    """ref: paddle.quantization.QAT."""

    def __init__(self, config: QuantConfig = None):
        self._config = config or _default_config()

    def quantize(self, model: Layer, inplace=True):
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        self._walk(model, prefix="")
        return model

    def _walk(self, layer: Layer, prefix: str):
        for name, sub in list(layer._sub_layers.items()):
            if sub is None:
                continue
            full = f"{prefix}.{name}" if prefix else name
            wrap = _WRAP.get(type(sub))
            if wrap is not None:
                act_f, w_f = self._config.lookup(sub, full)
                if act_f is None and w_f is None:
                    continue
                layer._sub_layers[name] = wrap(
                    sub,
                    activation_quanter=act_f() if act_f else None,
                    weight_quanter=w_f() if w_f else None)
            else:
                self._walk(sub, full)

    def convert(self, model: Layer, inplace=True):
        """Materialize int8 inference layers from the QAT wrappers
        (Linear only; quantized conv serving falls back to fake-quant)."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        self._convert_walk(model)
        model.eval()
        return model

    def _convert_walk(self, layer: Layer):
        for name, sub in list(layer._sub_layers.items()):
            if sub is None:
                continue
            if isinstance(sub, QuantedLinear):
                inner = sub._inner
                wq = sub.weight_quanter
                bits = getattr(wq, "bit_length", 8)
                ax = getattr(wq, "channel_axis", 1)
                w = np.asarray(inner.weight._value, np.float32)
                qmax = float(2 ** (bits - 1) - 1)
                red_ax = 0 if ax == 1 else 1
                ws = np.maximum(np.abs(w).max(axis=red_ax), 1e-9)
                wsb = ws[None, :] if ax == 1 else ws[:, None]
                w_int8 = np.clip(np.round(w / wsb * qmax),
                                 -qmax, qmax).astype(np.int8)
                act_scale = None
                act_bits = 8
                aq = sub.activation_quanter
                if aq is not None and hasattr(aq, "scale"):
                    s = float(np.asarray(aq.scale._value))
                    if s > 0:
                        act_scale = jnp.float32(s)
                        act_bits = getattr(aq, "bit_length", 8)
                bias = inner.bias._value if inner.bias is not None else None
                layer._sub_layers[name] = Int8InferLinear(
                    w_int8, ws.astype(np.float32), bias, act_scale,
                    bit_length=bits, channel_axis=ax, act_bit_length=act_bits)
            elif isinstance(sub, Layer):
                # freeze any observers/quanters that stay in the graph
                # (e.g. inside QuantedConv2D): calibration ends at convert
                if hasattr(sub, "_frozen"):
                    sub._frozen = True
                self._convert_walk(sub)
