"""Quant-wrapped layers (ref: python/paddle/nn/quant/ qat layers).

QuantedLinear/QuantedConv2D wrap an existing float layer: activations pass
through the activation quanter, weights through the weight quanter, then
the original op runs. `convert()` (see qat.py) turns these into int8-
weight inference layers whose matmul runs on the int8 MXU path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd import apply_op
from ..nn import functional as F
from ..nn.layer import Layer
from ..tensor import Tensor, to_tensor

__all__ = ["QuantedLinear", "QuantedConv2D", "Int8InferLinear"]


class QuantedLinear(Layer):
    """QAT wrapper for nn.Linear."""

    def __init__(self, float_layer, activation_quanter=None,
                 weight_quanter=None):
        super().__init__()
        self._inner = float_layer
        self.activation_quanter = activation_quanter
        self.weight_quanter = weight_quanter

    @property
    def weight(self):
        return self._inner.weight

    @property
    def bias(self):
        return self._inner.bias

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self._inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return F.linear(x, w, self._inner.bias)


class QuantedConv2D(Layer):
    """QAT wrapper for nn.Conv2D."""

    def __init__(self, float_layer, activation_quanter=None,
                 weight_quanter=None):
        super().__init__()
        self._inner = float_layer
        self.activation_quanter = activation_quanter
        self.weight_quanter = weight_quanter

    @property
    def weight(self):
        return self._inner.weight

    @property
    def bias(self):
        return self._inner.bias

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self._inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        inner = self._inner
        return F.conv2d(x, w, inner.bias, inner._stride, inner._padding,
                        inner._dilation, inner._groups, inner._data_format)


class Int8InferLinear(Layer):
    """Converted inference layer: int8 weights + per-channel fp scales.

    The matmul computes in int8 x int8 -> int32 on the MXU
    (preferred_element_type=jnp.int32), then applies the combined
    activation/weight scales — the standard TPU int8 serving formulation.

    channel_axis: which weight axis [in, out] the scales index (1 =
    per-out-feature, the default; 0 = per-in-feature). bit_length is the
    WEIGHT grid; act_bit_length the activation grid (they can differ).
    """

    def __init__(self, w_int8, w_scale, bias, act_scale=None, bit_length=8,
                 channel_axis=1, act_bit_length=8):
        super().__init__()
        self.register_buffer("w_int8", to_tensor(w_int8))
        self.register_buffer("w_scale", to_tensor(w_scale))
        self.register_buffer("bias_t",
                             to_tensor(bias) if bias is not None else None)
        self.register_buffer(
            "act_scale",
            to_tensor(act_scale) if act_scale is not None else None)
        self.bit_length = bit_length
        self.act_bit_length = act_bit_length
        self.channel_axis = channel_axis

    def forward(self, x):
        w_qmax = float(2 ** (self.bit_length - 1) - 1)
        a_qmax = float(2 ** (self.act_bit_length - 1) - 1)
        ax = self.channel_axis

        def f(xv, w8, ws, *rest):
            rest = list(rest)
            asv = rest.pop(0) if self.act_scale is not None else None
            bv = rest.pop(0) if self.bias_t is not None else None
            if asv is not None and ax == 1 \
                    and self.bit_length == self.act_bit_length == 8:
                # int8 x int8 -> int32 MXU path: per-out-feature weight
                # scales factor out of the K-sum
                xq = jnp.clip(jnp.round(xv / jnp.maximum(asv, 1e-9)
                                        * a_qmax),
                              -a_qmax, a_qmax).astype(jnp.int8)
                acc = jax.lax.dot_general(
                    xq, w8, (((xq.ndim - 1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                out = acc.astype(jnp.float32) \
                    * (asv / a_qmax) * (ws[None, :] / w_qmax)
                return (out + bv if bv is not None else out).astype(xv.dtype)
            if asv is not None:
                # general case (per-in-feature scales / mixed bit widths):
                # fake-quant activations on THEIR grid, then float matmul
                s = jnp.maximum(asv, 1e-9)
                xv = (jnp.clip(jnp.round(xv / s * a_qmax), -a_qmax, a_qmax)
                      * s / a_qmax).astype(xv.dtype)
            wsb = ws[None, :] if ax == 1 else ws[:, None]
            # dequantized weights in the activation dtype keeps the matmul
            # on the bf16 MXU path for bf16 serving
            w = (w8.astype(jnp.float32) * (wsb / w_qmax)).astype(xv.dtype)
            out = xv @ w
            if bv is not None:
                out = out + bv
            return out.astype(xv.dtype)

        args = [x if isinstance(x, Tensor) else to_tensor(x),
                self.w_int8, self.w_scale]
        if self.act_scale is not None:
            args.append(self.act_scale)
        if self.bias_t is not None:
            args.append(self.bias_t)
        return apply_op(f, *args)
