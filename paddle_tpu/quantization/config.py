"""QuantConfig (ref: python/paddle/quantization/config.py).

Maps layer types / names to (activation quanter factory, weight quanter
factory). The default covers Linear and Conv2D like the reference's
`add_type_config` common path.
"""
from __future__ import annotations

__all__ = ["QuantConfig"]


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        """activation/weight: factory callables returning quanter/observer
        Layers (e.g. `lambda: FakeQuanterWithAbsMax(8)`), applied as the
        global default."""
        self._default = (activation, weight)
        self._type_configs = {}
        self._name_configs = {}

    def add_type_config(self, layer_types, activation=None, weight=None):
        if not isinstance(layer_types, (list, tuple)):
            layer_types = [layer_types]
        for t in layer_types:
            self._type_configs[t] = (activation, weight)
        return self

    def add_name_config(self, names, activation=None, weight=None):
        if not isinstance(names, (list, tuple)):
            names = [names]
        for n in names:
            self._name_configs[n] = (activation, weight)
        return self

    def lookup(self, layer, name):
        if name in self._name_configs:
            return self._name_configs[name]
        for t, cfg in self._type_configs.items():
            if isinstance(layer, t):
                return cfg
        return self._default
