"""Fake quanters (ref: python/paddle/quantization/quanters/abs_max.py).

`quant_dequant` is the core primitive: symmetric int-k fake quantization
with a straight-through gradient, expressed as `x + sg(qdq(x) - x)` so it
is exact under jit/grad AND on the eager tape without custom vjp rules.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd import apply_op
from ..nn.layer import Layer
from ..tensor import Tensor, to_tensor

__all__ = ["quant_dequant", "FakeQuanterWithAbsMax",
           "FakeQuanterChannelWiseAbsMax"]


def _qdq(x, scale, qmax):
    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


def quant_dequant(x, scale, bit_length=8, channel_axis=None, name=None):
    """Symmetric fake quant-dequant with straight-through gradients.

    scale: per-tensor scalar or per-channel vector (paired with
    channel_axis)."""
    qmax = float(2 ** (bit_length - 1) - 1)

    def f(xv, sv):
        if channel_axis is not None:
            shape = [1] * xv.ndim
            shape[channel_axis] = -1
            sv = sv.reshape(shape)
        qd = _qdq(xv, sv, qmax)
        # straight-through: forward = qd, gradient = identity w.r.t. x
        return xv + jax.lax.stop_gradient(qd - xv)

    t = x if isinstance(x, Tensor) else to_tensor(x)
    s = scale if isinstance(scale, Tensor) else to_tensor(scale)
    return apply_op(f, t, s)


class FakeQuanterWithAbsMax(Layer):
    """ref: FakeQuanterWithAbsMaxObserver — per-tensor absmax scale with
    EMA tracking during training (scale is a buffer: it rides the jitted
    step's buffer dict, no host sync)."""

    def __init__(self, bit_length=8, moving_rate=0.9, name=None):
        super().__init__()
        self.bit_length = bit_length
        self.moving_rate = moving_rate
        self.register_buffer("scale", to_tensor(jnp.zeros((), jnp.float32)))

    def forward(self, x):
        t = x if isinstance(x, Tensor) else to_tensor(x)
        if self.training:
            cur = apply_op(lambda a: jnp.max(jnp.abs(a)).astype(jnp.float32),
                           t, differentiable=False)
            r = self.moving_rate
            new_scale = apply_op(
                lambda s, c: jnp.where(s > 0, r * s + (1 - r) * c, c),
                self.scale, cur, differentiable=False)
            # IN-PLACE buffer value update (BatchNorm pattern): the Engine's
            # functional_call captures the buffer OBJECT, so rebinding the
            # attribute would lose the traced update
            self.scale._value = new_scale._value
            use = new_scale
        else:
            use = self.scale
        out = quant_dequant(t, use, self.bit_length)
        # uncalibrated (scale == 0, e.g. eval before any training forward):
        # pass through unquantized instead of collapsing everything to ~0
        return apply_op(lambda o, xv, s: jnp.where(s > 0, o, xv),
                        out, t, use)


class FakeQuanterChannelWiseAbsMax(Layer):
    """ref: FakeQuanterChannelWiseAbsMax — per-output-channel scales for
    weights (axis 0 for Linear [in,out]->axis 1? The reference quantizes
    conv weights per out-channel (axis 0 of OIHW) and linear weights per
    out-feature (axis 1 of [in, out]))."""

    def __init__(self, bit_length=8, channel_axis=0, name=None):
        super().__init__()
        self.bit_length = bit_length
        self.channel_axis = channel_axis

    def forward(self, w):
        t = w if isinstance(w, Tensor) else to_tensor(w)
        ax = self.channel_axis

        def scales(a):
            red = tuple(i for i in range(a.ndim) if i != ax)
            return jnp.max(jnp.abs(a), axis=red).astype(jnp.float32)
        s = apply_op(scales, t, differentiable=False)
        return quant_dequant(t, s, self.bit_length, channel_axis=ax)
