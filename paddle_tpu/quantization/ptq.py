"""PTQ driver (ref: python/paddle/quantization/ptq.py).

`PTQ(config).quantize(model)` inserts observers; run calibration forwards;
`convert(model)` freezes observed scales into int8 inference layers.
"""
from __future__ import annotations

from ..nn.layer import Layer
from ..nn.layers_common import Linear
from ..nn.layers_conv import Conv2D
from .config import QuantConfig
from .layers import QuantedConv2D, QuantedLinear
from .observers import AbsmaxObserver
from .qat import QAT
from .quanters import FakeQuanterChannelWiseAbsMax

__all__ = ["PTQ"]


def _default_ptq_config():
    cfg = QuantConfig(
        activation=lambda: AbsmaxObserver(8),
        weight=lambda: FakeQuanterChannelWiseAbsMax(8, channel_axis=1))
    cfg.add_type_config(
        Conv2D,
        activation=lambda: AbsmaxObserver(8),
        weight=lambda: FakeQuanterChannelWiseAbsMax(8, channel_axis=0))
    return cfg


class PTQ(QAT):
    """ref: paddle.quantization.PTQ — observer insertion + convert. The
    quantize/convert walks are shared with QAT; only the default config
    (observers instead of trainable fake-quanters) differs."""

    def __init__(self, config: QuantConfig = None):
        super().__init__(config or _default_ptq_config())
