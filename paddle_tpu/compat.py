"""Checkpoint compatibility with the reference framework
(ref: the .pdparams/.pdopt save format of paddle.save —
python/paddle/framework/io.py).

The reference pickles a dict of {param_name: numpy array} (state-dict
saves convert tensors to ndarrays before pickling; some versions pickle
tensor wrappers that reduce to an ndarray payload). `load_pdparams` reads
both so real Paddle checkpoints migrate directly:

    state = paddle_tpu.compat.load_pdparams("model.pdparams")
    model.set_state_dict(state)

`paddle_tpu.load` also sniffs the format and delegates here, so plain
`paddle.load("model.pdparams")` works as advertised. `save_pdparams`
writes the reference layout for users round-tripping OFF TPU.
"""
from __future__ import annotations

import pickle

import numpy as np

__all__ = ["load_pdparams", "save_pdparams"]

# paddle globals that appear in checkpoints as tensor-REBUILD calls whose
# first ndarray argument is the data; these (and only these) degrade to a
# passthrough. Any other paddle.* global is an unsupported object save and
# fails loudly rather than corrupting the state dict.
_TENSOR_REBUILDERS = {
    ("paddle", "Tensor"),
    ("paddle.base.core", "eager"),
    ("paddle.fluid.core", "eager"),
    ("paddle.base.framework", "EagerParamBase"),
    ("paddle.fluid.framework", "ParamBase"),
    ("paddle.fluid.framework", "EagerParamBase"),
    ("paddle.framework.io", "_rebuild_tensor"),
    ("paddle.base.core", "_rebuild_tensor"),
}


class _CompatUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if (module, name) in _TENSOR_REBUILDERS:
            return _ndarray_passthrough
        if module == "paddle" or module.startswith("paddle."):
            raise pickle.UnpicklingError(
                f"unsupported paddle object in checkpoint: {module}.{name}. "
                "load_pdparams reads STATE-DICT saves ({name: array}); "
                "whole-object paddle.save(layer) checkpoints must be "
                "re-saved as state dicts in the reference framework first")
        return super().find_class(module, name)


class _ndarray_passthrough:
    """Stand-in for the reference's tensor rebuild callables: called with
    the pickled payload, returns the first ndarray argument."""

    def __new__(cls, *args, **kwargs):
        for a in args:
            if isinstance(a, np.ndarray):
                return a
        raise pickle.UnpicklingError(
            "paddle tensor rebuild carried no ndarray payload "
            f"(args={tuple(type(a).__name__ for a in args)})")


def load_pdparams(path, return_numpy=False):
    """Load a reference-framework .pdparams/.pdopt pickle into a state
    dict of Tensors (or raw ndarrays with return_numpy=True)."""
    with open(path, "rb") as f:
        state = _CompatUnpickler(f).load()
    if return_numpy:
        return state
    from .tensor import Tensor

    def wrap(x):
        if isinstance(x, np.ndarray):
            return Tensor(x)
        if isinstance(x, dict):
            return {k: wrap(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return type(x)(wrap(v) for v in x)
        return x

    return wrap(state)


def save_pdparams(state_dict, path, protocol=2):
    """Write a state dict in the reference's .pdparams layout (plain
    pickled {name: ndarray} — loadable by paddle.load)."""
    from .tensor import Tensor

    def unwrap(x):
        if isinstance(x, Tensor):
            return np.asarray(x._value)
        if isinstance(x, dict):
            return {k: unwrap(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return type(x)(unwrap(v) for v in x)
        return x

    with open(path, "wb") as f:
        pickle.dump(unwrap(state_dict), f, protocol=protocol)
