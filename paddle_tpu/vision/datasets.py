"""Vision datasets (ref: python/paddle/vision/datasets/*).

This environment has no network egress, so datasets parse local files when
present (MNIST idx / CIFAR pickle formats, identical parsers to the
reference) and otherwise fall back to a deterministic synthetic set with the
same shapes/dtypes — enough for pipelines, tests and benchmarks.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "SyntheticImageNet"]


def _synthetic_images(n, shape, n_classes, seed):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, n_classes, size=n).astype(np.int64)
    # class-dependent means so models can actually learn
    imgs = (rng.rand(n, *shape) * 64 +
            labels[:, None, None].reshape(n, *([1] * len(shape))) *
            (192.0 / max(n_classes - 1, 1))).astype(np.uint8)
    return imgs, labels


class MNIST(Dataset):
    NUM_CLASSES = 10

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        self.mode = mode
        self.transform = transform
        images = labels = None
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                images = np.frombuffer(f.read(), dtype=np.uint8
                                       ).reshape(n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                labels = np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)
        if images is None:
            n = 6000 if mode == "train" else 1000
            images, labels = _synthetic_images(
                n, (28, 28), 10, seed=0 if mode == "train" else 1)
        self.images = images
        self.labels = labels

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32)[None] / 255.0
        return img, np.int64(label)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2"):
        self.transform = transform
        images = labels = None
        if data_file and os.path.exists(data_file):
            batches = ([f"data_batch_{i}" for i in range(1, 6)]
                       if mode == "train" else ["test_batch"])
            imgs, labs = [], []
            with tarfile.open(data_file) as tf:
                for m in tf.getmembers():
                    base = os.path.basename(m.name)
                    if base in batches:
                        d = pickle.load(tf.extractfile(m), encoding="bytes")
                        imgs.append(d[b"data"].reshape(-1, 3, 32, 32))
                        labs.extend(d.get(b"labels", d.get(b"fine_labels")))
            if imgs:
                images = np.concatenate(imgs).transpose(0, 2, 3, 1)
                labels = np.asarray(labs, dtype=np.int64)
        if images is None:
            n = 5000 if mode == "train" else 1000
            images, labels = _synthetic_images(
                n, (32, 32, 3), self.NUM_CLASSES,
                seed=2 if mode == "train" else 3)
        self.images = images
        self.labels = labels

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32).transpose(2, 0, 1) / 255.0
        return img, np.int64(label)

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class SyntheticImageNet(Dataset):
    """Deterministic fake ImageNet for throughput benchmarking (the
    reference benchmarks use DALI/file pipelines; perf here is bounded by
    device compute, which is what bench.py measures)."""

    def __init__(self, n=1280, image_size=224, num_classes=1000,
                 transform=None, dtype=np.float32):
        rng = np.random.RandomState(42)
        self.labels = rng.randint(0, num_classes, size=n).astype(np.int64)
        self.n = n
        self.image_size = image_size
        self.transform = transform
        self.dtype = dtype
        self._cache = (rng.rand(64, 3, image_size, image_size) * 2 - 1).astype(dtype)

    def __getitem__(self, idx):
        img = self._cache[idx % len(self._cache)]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return self.n


# ---------------------------------------------------------------------
# Folder datasets (ref: python/paddle/vision/datasets/folder.py)
# ---------------------------------------------------------------------

IMAGE_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm",
                    ".tif", ".tiff", ".webp")


def image_load(path, backend=None):
    """Default image loader. backend=None/'numpy' returns an HWC uint8
    array (what this framework's numpy-based transforms consume);
    backend='pil' returns the PIL Image (reference default backend).
    ref: paddle.vision.image_load."""
    from PIL import Image
    with Image.open(path) as img:
        img = img.convert("RGB")
        if backend == "pil":
            img.load()
            return img
        return np.asarray(img, dtype=np.uint8)


def _has_valid_ext(path, extensions):
    return path.lower().endswith(tuple(e.lower() for e in extensions))


def _resolve_filter(extensions, is_valid_file):
    """One validity predicate from the (extensions, is_valid_file) pair;
    passing both is rejected like the reference does."""
    if extensions is not None and is_valid_file is not None:
        raise ValueError(
            "both 'extensions' and 'is_valid_file' were given — pass "
            "exactly one")
    if is_valid_file is not None:
        return is_valid_file, None
    if extensions is None:
        extensions = IMAGE_EXTENSIONS
    return (lambda p: _has_valid_ext(p, extensions)), extensions


def _iter_valid_files(directory, valid):
    for root, _, files in sorted(os.walk(directory, followlinks=True)):
        for fname in sorted(files):
            path = os.path.join(root, fname)
            if valid(path):
                yield path


def _make_samples(directory, class_to_idx, valid):
    samples = []
    for cls in sorted(class_to_idx):
        cdir = os.path.join(directory, cls)
        for path in _iter_valid_files(cdir, valid):
            samples.append((path, class_to_idx[cls]))
    return samples


class DatasetFolder(Dataset):
    """Generic `root/class_x/xxx.ext` directory-tree dataset
    (ref: paddle.vision.datasets.DatasetFolder — the workhorse for real
    image training directories).

    classes are the sorted sub-directory names of `root`; samples are
    (path, class_index) pairs; __getitem__ returns (image, label) with
    `transform` applied to the loaded image.
    """

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        super().__init__()
        self.root = root
        self.transform = transform
        self.loader = loader if loader is not None else image_load
        valid, self.extensions = _resolve_filter(extensions, is_valid_file)
        classes = sorted(e.name for e in os.scandir(root) if e.is_dir())
        if not classes:
            raise RuntimeError(f"no class directories found under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = _make_samples(root, self.class_to_idx, valid)
        if not self.samples:
            raise RuntimeError(
                f"found no valid files under {root}; supported "
                f"extensions: {self.extensions}")
        self.targets = [t for _, t in self.samples]

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(target)

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Unlabeled flat image set: every image under `root`, recursively
    (ref: paddle.vision.datasets.ImageFolder). __getitem__ returns
    [image] (a one-element list, matching the reference)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        super().__init__()
        self.root = root
        self.transform = transform
        self.loader = loader if loader is not None else image_load
        valid, extensions = _resolve_filter(extensions, is_valid_file)
        self.samples = list(_iter_valid_files(root, valid))
        if not self.samples:
            raise RuntimeError(
                f"found no valid files under {root}; supported "
                f"extensions: {extensions}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


__all__ += ["DatasetFolder", "ImageFolder", "image_load",
            "IMAGE_EXTENSIONS"]


class Flowers(Dataset):
    """Oxford 102 Flowers (ref: python/paddle/vision/datasets/flowers.py).

    data_file=(images_dir_or_tgz, labels_mat, setid_mat) parses the real
    release: jpg images, imagelabels.mat (1-based labels), setid.mat
    (trnid/valid/tstid index splits — mode train/valid/test). Without
    data_file: deterministic synthetic set with the same shapes."""

    NUM_CLASSES = 102
    _SPLIT_KEY = {"train": "trnid", "valid": "valid", "test": "tstid"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 n=128, image_size=64, backend=None):
        self.transform = transform
        self.backend = backend
        if data_file is not None:
            import scipy.io
            images, labels_mat, setid_mat = data_file
            labels = scipy.io.loadmat(labels_mat)["labels"].ravel()
            setid = scipy.io.loadmat(setid_mat)
            ids = setid[self._SPLIT_KEY[mode]].ravel()
            self._images_root = images
            self._tar = None
            self._tar_index = None
            if os.path.isfile(images) and tarfile.is_tarfile(images):
                # the release tarball itself: index members by basename,
                # read lazily (lock: TarFile handles are not thread-safe
                # under DataLoader workers)
                import threading
                self._tar_lock = threading.Lock()
                self._tar = tarfile.open(images, "r:*")
                self._tar_index = {
                    os.path.basename(m.name): m
                    for m in self._tar.getmembers() if m.isfile()}
            # image_%05d.jpg, 1-based ids; labels 1-based -> 0-based
            self.samples = [(f"image_{i:05d}.jpg", int(labels[i - 1]) - 1)
                            for i in ids]
            self._synthetic = None
            return
        imgs, labels = _synthetic_images(
            n, (image_size, image_size, 3), self.NUM_CLASSES,
            7 if mode == "train" else 8)
        self._synthetic = (imgs, labels)
        self._tar = None
        self.samples = list(range(n))

    def __getitem__(self, idx):
        if self._synthetic is not None:
            img, label = (self._synthetic[0][idx],
                          self._synthetic[1][idx])
        else:
            fname, label = self.samples[idx]
            if self._tar is not None:
                import io as _io
                from PIL import Image
                with self._tar_lock:
                    data = self._tar.extractfile(
                        self._tar_index[fname]).read()
                with Image.open(_io.BytesIO(data)) as im:
                    im = im.convert("RGB")
                    if self.backend == "pil":
                        im.load()
                        img = im
                    else:
                        img = np.asarray(im, dtype=np.uint8)
            else:
                img = image_load(os.path.join(self._images_root, fname),
                                 backend=self.backend)
            label = np.int64(label)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(label)

    def __len__(self):
        return len(self.samples)


class VOC2012(Dataset):
    """Pascal VOC 2012 segmentation (ref:
    python/paddle/vision/datasets/voc2012.py — (image, segmentation
    mask) pairs).

    data_file = the VOCdevkit/VOC2012 root (extracted): reads
    ImageSets/Segmentation/{train,val,trainval}.txt, JPEGImages/*.jpg
    and SegmentationClass/*.png. Without data_file: synthetic pairs."""

    _MODE_FILE = {"train": "train.txt", "valid": "val.txt",
                  "test": "val.txt", "trainval": "trainval.txt"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 n=64, image_size=64, backend=None):
        self.transform = transform
        self.backend = backend
        if data_file is not None:
            root = data_file
            lst = os.path.join(root, "ImageSets", "Segmentation",
                               self._MODE_FILE[mode])
            with open(lst) as f:
                names = [l.strip() for l in f if l.strip()]
            if not names:
                raise ValueError(f"empty split list {lst}")
            self._root = root
            self.samples = names
            self._synthetic = None
            return
        rng = np.random.RandomState(9 if mode == "train" else 10)
        self._synthetic = (
            (rng.rand(n, image_size, image_size, 3) * 255).astype(np.uint8),
            rng.randint(0, 21, (n, image_size, image_size)).astype(np.uint8))
        self.samples = list(range(n))

    def __getitem__(self, idx):
        if self._synthetic is not None:
            img, mask = self._synthetic[0][idx], self._synthetic[1][idx]
        else:
            name = self.samples[idx]
            img = image_load(os.path.join(self._root, "JPEGImages",
                                          name + ".jpg"),
                             backend=self.backend)
            from PIL import Image
            with Image.open(os.path.join(self._root, "SegmentationClass",
                                         name + ".png")) as m:
                mask = np.asarray(m, dtype=np.uint8)   # palette indices
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return len(self.samples)


__all__ += ["Flowers", "VOC2012"]
