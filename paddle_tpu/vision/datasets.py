"""Vision datasets (ref: python/paddle/vision/datasets/*).

This environment has no network egress, so datasets parse local files when
present (MNIST idx / CIFAR pickle formats, identical parsers to the
reference) and otherwise fall back to a deterministic synthetic set with the
same shapes/dtypes — enough for pipelines, tests and benchmarks.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "SyntheticImageNet"]


def _synthetic_images(n, shape, n_classes, seed):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, n_classes, size=n).astype(np.int64)
    # class-dependent means so models can actually learn
    imgs = (rng.rand(n, *shape) * 64 +
            labels[:, None, None].reshape(n, *([1] * len(shape))) *
            (192.0 / max(n_classes - 1, 1))).astype(np.uint8)
    return imgs, labels


class MNIST(Dataset):
    NUM_CLASSES = 10

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        self.mode = mode
        self.transform = transform
        images = labels = None
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                images = np.frombuffer(f.read(), dtype=np.uint8
                                       ).reshape(n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                labels = np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)
        if images is None:
            n = 6000 if mode == "train" else 1000
            images, labels = _synthetic_images(
                n, (28, 28), 10, seed=0 if mode == "train" else 1)
        self.images = images
        self.labels = labels

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32)[None] / 255.0
        return img, np.int64(label)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2"):
        self.transform = transform
        images = labels = None
        if data_file and os.path.exists(data_file):
            batches = ([f"data_batch_{i}" for i in range(1, 6)]
                       if mode == "train" else ["test_batch"])
            imgs, labs = [], []
            with tarfile.open(data_file) as tf:
                for m in tf.getmembers():
                    base = os.path.basename(m.name)
                    if base in batches:
                        d = pickle.load(tf.extractfile(m), encoding="bytes")
                        imgs.append(d[b"data"].reshape(-1, 3, 32, 32))
                        labs.extend(d.get(b"labels", d.get(b"fine_labels")))
            if imgs:
                images = np.concatenate(imgs).transpose(0, 2, 3, 1)
                labels = np.asarray(labs, dtype=np.int64)
        if images is None:
            n = 5000 if mode == "train" else 1000
            images, labels = _synthetic_images(
                n, (32, 32, 3), self.NUM_CLASSES,
                seed=2 if mode == "train" else 3)
        self.images = images
        self.labels = labels

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32).transpose(2, 0, 1) / 255.0
        return img, np.int64(label)

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class SyntheticImageNet(Dataset):
    """Deterministic fake ImageNet for throughput benchmarking (the
    reference benchmarks use DALI/file pipelines; perf here is bounded by
    device compute, which is what bench.py measures)."""

    def __init__(self, n=1280, image_size=224, num_classes=1000,
                 transform=None, dtype=np.float32):
        rng = np.random.RandomState(42)
        self.labels = rng.randint(0, num_classes, size=n).astype(np.int64)
        self.n = n
        self.image_size = image_size
        self.transform = transform
        self.dtype = dtype
        self._cache = (rng.rand(64, 3, image_size, image_size) * 2 - 1).astype(dtype)

    def __getitem__(self, idx):
        img = self._cache[idx % len(self._cache)]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return self.n
