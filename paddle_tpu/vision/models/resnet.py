"""ResNet family (ref: python/paddle/vision/models/resnet.py).

Public API stays NCHW for parity, but internally the stack is
NHWC-native on TPU (``layout="auto"``): the input is transposed ONCE at
network entry, every conv/pool/BN then runs channels-last with HWIO
kernels (nn.layers_conv.to_channels_last), and the boundary transposes
back only when a feature map leaves the network. This replaces the old
"NCHW + let XLA re-lay out per conv" seed behavior — the r4 fusion
audit and the MLPerf TPU scaling paper both pin the ResNet gap on
exactly those per-op relayouts. ``fused_bottleneck=True`` additionally
routes the bottleneck 1x1-conv+BN+ReLU(+residual) chains through the
Pallas kernel in ops/pallas/conv_bn_act.py (the diagnosed
HBM-bandwidth-bound op). bn momentum/epsilon match the reference
defaults.
"""
from __future__ import annotations

from ... import nn

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152", "wide_resnet50_2", "wide_resnet101_2", "resnext50_32x4d",
           "resnext101_32x4d", "resnext101_64x4d", "resnext152_64x4d",
           "SpaceToDepthStem", "space_to_depth", "s2d_weights_from_7x7"]


def _resolve_layout(layout):
    """'auto' -> NHWC on TPU (the conv units' native layout), NCHW
    elsewhere (CPU parity runs and checkpoint interop)."""
    lay = str(layout).upper()
    if lay == "AUTO":
        import jax
        return "NHWC" if jax.default_backend() == "tpu" else "NCHW"
    if lay not in ("NHWC", "NCHW"):
        raise ValueError(f"layout must be 'auto' | 'NHWC' | 'NCHW', "
                         f"got {layout!r}")
    return lay


def _fused_conv1x1_bn(x, conv, bn, residual=None, training=False):
    """One fused pass for a channels-last 1x1 conv + BatchNorm + ReLU
    (+ residual): y = relu((x @ W_hwio) * scale + shift [+ res]).

    Returns the output Tensor, or None when the fused path doesn't
    apply (NCHW weights, strided/grouped/biased conv, no BN affine, or
    train-mode batch stats where the Gram trick would cost more FLOPs
    than the conv — cin > cout). Train mode computes the batch stats of
    the conv output WITHOUT materializing it (conv1x1_batch_stats) and
    updates the BN running buffers exactly like F.batch_norm."""
    import jax as _jax
    import jax.numpy as jnp

    from ...autograd import apply_op
    from ...ops.pallas.conv_bn_act import (conv1x1_batch_stats,
                                           fused_conv1x1_bn_act)
    w = conv.weight
    pad = conv._padding
    padded = isinstance(pad, str) or (
        any(int(p) != 0 for p in pad) if isinstance(pad, (list, tuple))
        else int(pad) != 0)
    # getattr: after incubate.fuse_conv_bn the bn slot holds an Identity
    # (and the conv gained a bias) — the plain path handles that fine
    if (conv._weight_format != "HWIO" or conv.bias is not None
            or getattr(bn, "weight", None) is None
            or getattr(bn, "bias", None) is None
            or conv._groups != 1 or padded
            or any(s != 1 for s in conv._stride)
            or any(k != 1 for k in conv._kernel_size)):
        return None
    cin, cout = int(w.shape[-2]), int(w.shape[-1])
    use_batch = training and not bn._use_global_stats
    if use_batch and cin > cout:
        return None
    eps = bn._epsilon
    if use_batch:
        mean, var = apply_op(
            lambda a, ww: conv1x1_batch_stats(
                a.reshape(-1, a.shape[-1]),
                ww.reshape(ww.shape[-2], ww.shape[-1])), x, w)
        m_rows = 1
        for d in x.shape[:-1]:
            m_rows *= int(d)
        unbiased = var * (m_rows / max(m_rows - 1.0, 1.0))
        rm, rv = bn._mean, bn._variance
        mom = bn._momentum
        rm._inplace(rm * mom + mean.detach() * (1.0 - mom))
        rv._inplace(rv * mom + unbiased.detach() * (1.0 - mom))
    else:
        mean, var = bn._mean, bn._variance
    interp = _jax.default_backend() != "tpu"

    def f(a, ww, g, b, mu, v, *res):
        scale = g.astype(jnp.float32) * _jax.lax.rsqrt(
            v.astype(jnp.float32) + eps)
        shift = b.astype(jnp.float32) - mu.astype(jnp.float32) * scale
        lead = a.shape[:-1]
        m = 1
        for d in lead:
            m *= d
        w2 = ww.reshape(ww.shape[-2], ww.shape[-1])
        r2 = res[0].reshape(m, res[0].shape[-1]) if res else None
        y2 = fused_conv1x1_bn_act(a.reshape(m, a.shape[-1]), w2, scale,
                                  shift, r2, True, 0, interp)
        return y2.reshape(lead + (w2.shape[-1],))

    args = [x, w, bn.weight, bn.bias, mean, var]
    if residual is not None:
        args.append(residual)
    return apply_op(f, *args)


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False)
        self.bn1 = norm_layer(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = norm_layer(width)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation,
                               stride=stride, groups=groups,
                               dilation=dilation, bias_attr=False)
        self.bn2 = norm_layer(width)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample
        self.stride = stride
        self._fused = False

    def forward(self, x):
        if self._fused:
            out = self._forward_fused(x)
            if out is not None:
                return out
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)

    def _forward_fused(self, x):
        """Bottleneck with the 1x1 chains through the Pallas fused
        kernel (NHWC only). conv1 fuses where the stats are free
        (eval / use_global_stats); conv3+residual+relu — the diagnosed
        bandwidth-bound chain — fuses in train mode too (its batch
        stats cost Cin/Cout = 1/4 of the conv via the Gram trick).
        Falls back per-conv, and returns None (caller runs the plain
        path) when the block isn't channels-last at all."""
        if self.conv1._weight_format != "HWIO":
            return None
        out = _fused_conv1x1_bn(x, self.conv1, self.bn1,
                                training=self.training)
        if out is None:
            out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        identity = x if self.downsample is None else self.downsample(x)
        fused3 = _fused_conv1x1_bn(out, self.conv3, self.bn3, identity,
                                   training=self.training)
        if fused3 is None:
            return self.relu(self.bn3(self.conv3(out)) + identity)
        return fused3


def space_to_depth(x, block_size, data_format="NCHW"):
    """NCHW: [B,C,H,W] -> [B, C*b*b, H/b, W/b]; NHWC: [B,H,W,C] ->
    [B, H/b, W/b, C*b*b]. Channel index = (c, di, dj) in BOTH layouts,
    so s2d_weights_from_7x7 kernels are layout-independent (modulo the
    OIHW->HWIO transpose). Pure reshape/transpose — free under XLA."""
    b = int(block_size)
    if data_format == "NHWC":
        B, H, W, C = x.shape
        x = x.reshape([B, H // b, b, W // b, b, C])
        x = x.transpose([0, 1, 3, 5, 2, 4])
        return x.reshape([B, H // b, W // b, C * b * b])
    B, C, H, W = x.shape
    x = x.reshape([B, C, H // b, b, W // b, b])
    x = x.transpose([0, 1, 3, 5, 2, 4])
    return x.reshape([B, C * b * b, H // b, W // b])


class SpaceToDepthStem(nn.Layer):
    """MLPerf-TPU-style replacement for the 7x7/s2 stem conv.

    The 7x7 stride-2 conv on a 3-channel input is the worst op in the
    network for the MXU: C_in=3 wastes 125/128 of the contraction lanes
    and stride 2 halves window reuse. Packing 2x2 pixel blocks into
    channels (space-to-depth) turns it into an exactly-equivalent 4x4
    stride-1 conv over 12 input channels — 4x the lane utilization, no
    strided access. Equivalence: pad the 7x7 kernel to 8x8 (one zero row
    on top, one zero col on the left), then regroup taps by pixel parity;
    `s2d_weights_from_7x7` performs that mapping so reference-trained
    weights load exactly.
    ref: MLPerf ResNet TPU recipes (conv0 space-to-depth);
    python/paddle/vision/models/resnet.py keeps the plain 7x7.
    """

    def __init__(self, out_channels=64):
        super().__init__()
        self.conv = nn.Conv2D(12, out_channels, 4, stride=1,
                              padding=[2, 1, 2, 1], bias_attr=False)

    def forward(self, x):
        cl = self.conv._weight_format == "HWIO"
        h, w = (x.shape[1], x.shape[2]) if cl else (x.shape[2], x.shape[3])
        if h % 2 or w % 2:
            raise ValueError(
                f"SpaceToDepthStem needs even input H/W (got {h}x{w}): the "
                "2x2 pixel packing has no exact 7x7/s2 equivalent on odd "
                "sizes — pad the input or use the default stem "
                "(s2d_stem=False)")
        return self.conv(space_to_depth(x, 2,
                                        "NHWC" if cl else "NCHW"))


def s2d_weights_from_7x7(w7):
    """Convert a [O,3,7,7] stem kernel to the exactly-equivalent
    [O,12,4,4] space-to-depth kernel (see SpaceToDepthStem)."""
    import numpy as np
    w7 = np.asarray(w7)
    o = w7.shape[0]
    w = np.zeros((o, 12, 4, 4), w7.dtype)
    for c in range(3):
        for di in range(2):
            for dj in range(2):
                for p in range(4):
                    for q in range(4):
                        u, v = 2 * p + di - 1, 2 * q + dj - 1
                        if 0 <= u < 7 and 0 <= v < 7:
                            w[:, c * 4 + di * 2 + dj, p, q] = w7[:, c, u, v]
    return w


class ResNet(nn.Layer):
    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1, s2d_stem=False, layout="auto",
                 fused_bottleneck=False):
        super().__init__()
        self._layout = "NCHW"  # build in the reference layout first
        self._fused_bottleneck = False
        target_layout = _resolve_layout(layout)
        if fused_bottleneck and target_layout != "NHWC":
            raise ValueError(
                "fused_bottleneck requires the NHWC layout (pass "
                "layout='NHWC', or 'auto' on a TPU backend): the Pallas "
                "kernel consumes channels-last 1x1 convs")
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self._norm_layer = nn.BatchNorm2D
        self.inplanes = 64
        self.dilation = 1

        if s2d_stem:
            self.conv1 = SpaceToDepthStem(self.inplanes)
        else:
            self.conv1 = nn.Conv2D(3, self.inplanes, kernel_size=7, stride=2,
                                   padding=3, bias_attr=False)
        self.bn1 = self._norm_layer(self.inplanes)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)
        if target_layout == "NHWC":
            self.convert_to_nhwc()
        if fused_bottleneck:
            self._arm_fused_bottleneck()

    def convert_to_nhwc(self):
        """Switch the whole stack to the TPU-native channels-last
        pipeline IN PLACE: conv kernels re-stored HWIO, BN over the
        trailing axis, pools channel-last. The public forward contract
        is unchanged (NCHW in/out) — the layout changes exactly once at
        entry/exit instead of per op. Call AFTER loading NCHW
        checkpoints (weights transpose losslessly); idempotent."""
        from ...nn.layers_conv import to_channels_last
        to_channels_last(self)
        self._layout = "NHWC"
        return self

    def _arm_fused_bottleneck(self):
        if self._layout != "NHWC":
            raise ValueError("fused_bottleneck requires the NHWC layout "
                             "(convert_to_nhwc() first)")
        self._fused_bottleneck = True
        for _, sub in self.named_sublayers():
            if isinstance(sub, BottleneckBlock):
                sub._fused = True
        return self

    def _make_layer(self, block, planes, blocks, stride=1, dilate=False):
        norm_layer = self._norm_layer
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False),
                norm_layer(planes * block.expansion),
            )
        layers = [block(self.inplanes, planes, stride, downsample,
                        self.groups, self.base_width, self.dilation,
                        norm_layer)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width,
                                norm_layer=norm_layer))
        return nn.Sequential(*layers)

    def forward(self, x):
        nhwc = self._layout == "NHWC"
        if nhwc:
            # the single boundary transpose: everything below runs
            # channels-last, no per-op relayout
            x = x.transpose([0, 2, 3, 1])
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            if nhwc and not self.with_pool:
                # flatten order must match the NCHW-trained fc
                x = x.transpose([0, 3, 1, 2])
            x = x.flatten(1)
            x = self.fc(x)
        elif nhwc:
            x = x.transpose([0, 3, 1, 2])  # feature maps leave as NCHW
        return x


def _resnet(block, depth, pretrained=False, **kwargs):
    from ._utils import load_pretrained
    if pretrained:
        # checkpoints store the reference NCHW/OIHW layout: build NCHW,
        # load, then convert — conv kernels transpose losslessly
        layout = _resolve_layout(kwargs.pop("layout", "auto"))
        fused = kwargs.pop("fused_bottleneck", False)
        if fused and layout != "NHWC":
            raise ValueError("fused_bottleneck requires the NHWC layout")
        model = load_pretrained(
            lambda: ResNet(block, depth, layout="NCHW", **kwargs),
            pretrained, arch=f"resnet{depth}")
        if layout == "NHWC":
            model.convert_to_nhwc()
            if fused:
                model._arm_fused_bottleneck()
        return model
    return load_pretrained(lambda: ResNet(block, depth, **kwargs), pretrained,
                           arch=f"resnet{depth}")


def resnet18(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 18, pretrained, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 34, pretrained, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, pretrained, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, pretrained, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 152, pretrained, **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    kwargs["width"] = 128
    return _resnet(BottleneckBlock, 50, pretrained, **kwargs)


def wide_resnet101_2(pretrained=False, **kwargs):
    kwargs["width"] = 128
    return _resnet(BottleneckBlock, 101, pretrained, **kwargs)


def resnext50_32x4d(pretrained=False, **kwargs):
    kwargs["groups"] = 32
    kwargs["width"] = 4
    return _resnet(BottleneckBlock, 50, pretrained, **kwargs)


def resnext101_32x4d(pretrained=False, **kwargs):
    kwargs["groups"] = 32
    kwargs["width"] = 4
    return _resnet(BottleneckBlock, 101, pretrained, **kwargs)


def resnext101_64x4d(pretrained=False, **kwargs):
    kwargs["groups"] = 64
    kwargs["width"] = 4
    return _resnet(BottleneckBlock, 101, pretrained, **kwargs)


def resnext152_64x4d(pretrained=False, **kwargs):
    kwargs["groups"] = 64
    kwargs["width"] = 4
    return _resnet(BottleneckBlock, 152, pretrained, **kwargs)
