"""ResNet family (ref: python/paddle/vision/models/resnet.py).

Layout kept NCHW for API parity; XLA re-lays out to NHWC for the TPU conv
units automatically. bn momentum/epsilon match the reference defaults.
"""
from __future__ import annotations

from ... import nn

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152", "wide_resnet50_2", "wide_resnet101_2", "resnext50_32x4d",
           "resnext101_32x4d", "resnext101_64x4d", "resnext152_64x4d",
           "SpaceToDepthStem", "space_to_depth", "s2d_weights_from_7x7"]


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False)
        self.bn1 = norm_layer(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = norm_layer(width)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation,
                               stride=stride, groups=groups,
                               dilation=dilation, bias_attr=False)
        self.bn2 = norm_layer(width)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


def space_to_depth(x, block_size):
    """[B,C,H,W] -> [B, C*b*b, H/b, W/b]; channel index = (c, di, dj).
    Pure reshape/transpose — free under XLA (layout change only)."""
    b = int(block_size)
    B, C, H, W = x.shape
    x = x.reshape([B, C, H // b, b, W // b, b])
    x = x.transpose([0, 1, 3, 5, 2, 4])
    return x.reshape([B, C * b * b, H // b, W // b])


class SpaceToDepthStem(nn.Layer):
    """MLPerf-TPU-style replacement for the 7x7/s2 stem conv.

    The 7x7 stride-2 conv on a 3-channel input is the worst op in the
    network for the MXU: C_in=3 wastes 125/128 of the contraction lanes
    and stride 2 halves window reuse. Packing 2x2 pixel blocks into
    channels (space-to-depth) turns it into an exactly-equivalent 4x4
    stride-1 conv over 12 input channels — 4x the lane utilization, no
    strided access. Equivalence: pad the 7x7 kernel to 8x8 (one zero row
    on top, one zero col on the left), then regroup taps by pixel parity;
    `s2d_weights_from_7x7` performs that mapping so reference-trained
    weights load exactly.
    ref: MLPerf ResNet TPU recipes (conv0 space-to-depth);
    python/paddle/vision/models/resnet.py keeps the plain 7x7.
    """

    def __init__(self, out_channels=64):
        super().__init__()
        self.conv = nn.Conv2D(12, out_channels, 4, stride=1,
                              padding=[2, 1, 2, 1], bias_attr=False)

    def forward(self, x):
        h, w = x.shape[2], x.shape[3]
        if h % 2 or w % 2:
            raise ValueError(
                f"SpaceToDepthStem needs even input H/W (got {h}x{w}): the "
                "2x2 pixel packing has no exact 7x7/s2 equivalent on odd "
                "sizes — pad the input or use the default stem "
                "(s2d_stem=False)")
        return self.conv(space_to_depth(x, 2))


def s2d_weights_from_7x7(w7):
    """Convert a [O,3,7,7] stem kernel to the exactly-equivalent
    [O,12,4,4] space-to-depth kernel (see SpaceToDepthStem)."""
    import numpy as np
    w7 = np.asarray(w7)
    o = w7.shape[0]
    w = np.zeros((o, 12, 4, 4), w7.dtype)
    for c in range(3):
        for di in range(2):
            for dj in range(2):
                for p in range(4):
                    for q in range(4):
                        u, v = 2 * p + di - 1, 2 * q + dj - 1
                        if 0 <= u < 7 and 0 <= v < 7:
                            w[:, c * 4 + di * 2 + dj, p, q] = w7[:, c, u, v]
    return w


class ResNet(nn.Layer):
    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1, s2d_stem=False):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self._norm_layer = nn.BatchNorm2D
        self.inplanes = 64
        self.dilation = 1

        if s2d_stem:
            self.conv1 = SpaceToDepthStem(self.inplanes)
        else:
            self.conv1 = nn.Conv2D(3, self.inplanes, kernel_size=7, stride=2,
                                   padding=3, bias_attr=False)
        self.bn1 = self._norm_layer(self.inplanes)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1, dilate=False):
        norm_layer = self._norm_layer
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False),
                norm_layer(planes * block.expansion),
            )
        layers = [block(self.inplanes, planes, stride, downsample,
                        self.groups, self.base_width, self.dilation,
                        norm_layer)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width,
                                norm_layer=norm_layer))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def _resnet(block, depth, pretrained=False, **kwargs):
    from ._utils import load_pretrained
    return load_pretrained(lambda: ResNet(block, depth, **kwargs), pretrained,
                           arch=f"resnet{depth}")


def resnet18(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 18, pretrained, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 34, pretrained, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, pretrained, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, pretrained, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 152, pretrained, **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    kwargs["width"] = 128
    return _resnet(BottleneckBlock, 50, pretrained, **kwargs)


def wide_resnet101_2(pretrained=False, **kwargs):
    kwargs["width"] = 128
    return _resnet(BottleneckBlock, 101, pretrained, **kwargs)


def resnext50_32x4d(pretrained=False, **kwargs):
    kwargs["groups"] = 32
    kwargs["width"] = 4
    return _resnet(BottleneckBlock, 50, pretrained, **kwargs)


def resnext101_32x4d(pretrained=False, **kwargs):
    kwargs["groups"] = 32
    kwargs["width"] = 4
    return _resnet(BottleneckBlock, 101, pretrained, **kwargs)


def resnext101_64x4d(pretrained=False, **kwargs):
    kwargs["groups"] = 64
    kwargs["width"] = 4
    return _resnet(BottleneckBlock, 101, pretrained, **kwargs)


def resnext152_64x4d(pretrained=False, **kwargs):
    kwargs["groups"] = 64
    kwargs["width"] = 4
    return _resnet(BottleneckBlock, 152, pretrained, **kwargs)
