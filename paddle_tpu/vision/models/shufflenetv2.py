"""ShuffleNetV2 (ref: python/paddle/vision/models/shufflenetv2.py).

channel_shuffle is a pure reshape/transpose — free under XLA fusion.
"""
from __future__ import annotations

from ...tensor_ops.manip import concat
from ... import nn
from ._utils import load_pretrained

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0", "shufflenet_v2_swish"]

_STAGE_OUT = {
    0.25: (24, 24, 48, 96, 512), 0.33: (24, 32, 64, 128, 512),
    0.5: (24, 48, 96, 192, 1024), 1.0: (24, 116, 232, 464, 1024),
    1.5: (24, 176, 352, 704, 1024), 2.0: (24, 244, 488, 976, 2048)}
_STAGE_REPEATS = (4, 8, 4)


def channel_shuffle(x, groups):
    b, c, h, w = x.shape
    x = x.reshape([b, groups, c // groups, h, w])
    x = x.transpose([0, 2, 1, 3, 4])
    return x.reshape([b, c, h, w])


def _act(name):
    return nn.Swish() if name == "swish" else nn.ReLU()


class InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch = out_c // 2
        if stride == 1:
            self.branch2 = self._main(in_c // 2, branch, stride, act)
        else:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=stride, padding=1,
                          groups=in_c, bias_attr=False),
                nn.BatchNorm2D(in_c),
                nn.Conv2D(in_c, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), _act(act))
            self.branch2 = self._main(in_c, branch, stride, act)

    @staticmethod
    def _main(in_c, out_c, stride, act):
        return nn.Sequential(
            nn.Conv2D(in_c, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c), _act(act),
            nn.Conv2D(out_c, out_c, 3, stride=stride, padding=1,
                      groups=out_c, bias_attr=False),
            nn.BatchNorm2D(out_c),
            nn.Conv2D(out_c, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c), _act(act))

    def forward(self, x):
        if self.stride == 1:
            half = x.shape[1] // 2
            x1, x2 = x[:, :half], x[:, half:]
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        assert scale in _STAGE_OUT, f"supported scales: {sorted(_STAGE_OUT)}"
        outs = _STAGE_OUT[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, outs[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(outs[0]), _act(act))
        self.max_pool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_c = outs[0]
        for out_c, repeats in zip(outs[1:4], _STAGE_REPEATS):
            stages.append(InvertedResidual(in_c, out_c, 2, act))
            for _ in range(repeats - 1):
                stages.append(InvertedResidual(out_c, out_c, 1, act))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_c, outs[4], 1, bias_attr=False),
            nn.BatchNorm2D(outs[4]), _act(act))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(outs[4], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.max_pool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return load_pretrained(lambda: ShuffleNetV2(0.25, **kw), pretrained, arch="shufflenet_v2_x0_25")


def shufflenet_v2_x0_33(pretrained=False, **kw):
    return load_pretrained(lambda: ShuffleNetV2(0.33, **kw), pretrained, arch="shufflenet_v2_x0_33")


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return load_pretrained(lambda: ShuffleNetV2(0.5, **kw), pretrained, arch="shufflenet_v2_x0_5")


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return load_pretrained(lambda: ShuffleNetV2(1.0, **kw), pretrained, arch="shufflenet_v2_x1_0")


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return load_pretrained(lambda: ShuffleNetV2(1.5, **kw), pretrained, arch="shufflenet_v2_x1_5")


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return load_pretrained(lambda: ShuffleNetV2(2.0, **kw), pretrained, arch="shufflenet_v2_x2_0")


def shufflenet_v2_swish(pretrained=False, **kw):
    return load_pretrained(lambda: ShuffleNetV2(1.0, act="swish", **kw), pretrained, arch="shufflenet_v2_swish")
