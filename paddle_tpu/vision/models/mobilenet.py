"""MobileNet v1/v2/v3 (ref: python/paddle/vision/models/mobilenetv1.py,
mobilenetv2.py, mobilenetv3.py).

Depthwise convs lower to XLA grouped convolutions; on TPU these run on the
vector unit, so MobileNets are bandwidth-bound — exactly like the
reference's cuDNN depthwise path. Scale multipliers and the v3 SE +
hard-swish structure follow the reference configs.
"""
from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV1", "MobileNetV2", "MobileNetV3Small",
           "MobileNetV3Large", "mobilenet_v1", "mobilenet_v2",
           "mobilenet_v3_small", "mobilenet_v3_large"]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


from ._utils import ConvBNLayer, load_pretrained


class DepthwiseSeparable(nn.Layer):
    """ref mobilenetv1.py DepthwiseSeparable: dw 3x3 + pw 1x1."""

    def __init__(self, in_c, out_c1, out_c2, num_groups, stride, scale):
        super().__init__()
        c1 = int(out_c1 * scale)
        self.dw = ConvBNLayer(in_c, c1, 3, stride=stride, padding=1,
                              groups=int(num_groups * scale))
        self.pw = ConvBNLayer(c1, int(out_c2 * scale), 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = scale
        self.conv1 = ConvBNLayer(3, int(32 * s), 3, stride=2, padding=1)
        cfg = [  # in, out1, out2, groups, stride
            (32, 32, 64, 32, 1), (64, 64, 128, 64, 2),
            (128, 128, 128, 128, 1), (128, 128, 256, 128, 2),
            (256, 256, 256, 256, 1), (256, 256, 512, 256, 2),
            (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1), (512, 512, 1024, 512, 2),
            (1024, 1024, 1024, 1024, 1)]
        self.blocks = nn.Sequential(*[
            DepthwiseSeparable(int(i * s), o1, o2, g, st, s)
            for i, o1, o2, g, st in cfg])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * s), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


class InvertedResidual(nn.Layer):
    """ref mobilenetv2.py InvertedResidual: expand pw -> dw -> project."""

    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNLayer(inp, hidden, 1, act="relu6"))
        layers += [
            ConvBNLayer(hidden, hidden, 3, stride=stride, padding=1,
                        groups=hidden, act="relu6"),
            ConvBNLayer(hidden, oup, 1, act=None)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        feats = [ConvBNLayer(3, in_c, 3, stride=2, padding=1, act="relu6")]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                feats.append(InvertedResidual(in_c, out_c,
                                              s if i == 0 else 1, t))
                in_c = out_c
        feats.append(ConvBNLayer(in_c, last_c, 1, act="relu6"))
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class SqueezeExcitation(nn.Layer):
    """ref mobilenetv3.py SqueezeExcitation (hardsigmoid gate)."""

    def __init__(self, c, squeeze_c):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, squeeze_c, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(squeeze_c, c, 1)
        self.hs = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hs(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _V3Block(nn.Layer):
    def __init__(self, in_c, exp_c, out_c, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if exp_c != in_c:
            layers.append(ConvBNLayer(in_c, exp_c, 1, act=act))
        layers.append(ConvBNLayer(exp_c, exp_c, k, stride=stride,
                                  padding=k // 2, groups=exp_c, act=act))
        if use_se:
            layers.append(SqueezeExcitation(exp_c,
                                            _make_divisible(exp_c // 4)))
        layers.append(ConvBNLayer(exp_c, out_c, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


# k, exp, out, se, act, stride — ref mobilenetv3.py NET_CONFIG
_V3_LARGE = [
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1)]
_V3_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1)]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, last_c, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        feats = [ConvBNLayer(3, in_c, 3, stride=2, padding=1,
                             act="hardswish")]
        for k, exp, out, se, act, s in cfg:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            feats.append(_V3Block(in_c, exp_c, out_c, k, s, se, act))
            in_c = out_c
        exp_out = _make_divisible(last_exp * scale)
        feats.append(ConvBNLayer(in_c, exp_out, 1, act="hardswish"))
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(exp_out, last_c), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 576, 1024, scale, num_classes, with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 960, 1280, scale, num_classes, with_pool)


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return load_pretrained(lambda: MobileNetV1(scale=scale, **kwargs), pretrained, arch="mobilenet_v1")


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return load_pretrained(lambda: MobileNetV2(scale=scale, **kwargs), pretrained, arch="mobilenet_v2")


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return load_pretrained(lambda: MobileNetV3Small(scale=scale, **kwargs), pretrained, arch="mobilenet_v3_small")


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return load_pretrained(lambda: MobileNetV3Large(scale=scale, **kwargs), pretrained, arch="mobilenet_v3_large")
