"""Shared vision-model building blocks."""
from __future__ import annotations

from ... import nn


def check_pretrained(pretrained):
    """ref: the load_dygraph_pretrain path — this offline environment ships
    no weight files, so fail fast instead of silently returning random
    init."""
    if pretrained:
        raise NotImplementedError("no pretrained weights in this environment")


class ConvBNLayer(nn.Layer):
    """Conv2D + BatchNorm2D + optional activation — the block every conv
    net in the zoo repeats (ref: ConvBNLayer in each
    python/paddle/vision/models/*.py)."""

    _ACTS = {"relu": nn.ReLU, "relu6": nn.ReLU6, "hardswish": nn.Hardswish,
             "swish": nn.Swish, None: None}

    def __init__(self, in_c, out_c, k, stride=1, padding=0, groups=1,
                 act="relu"):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                              groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = self._ACTS[act]() if self._ACTS[act] else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act else x
