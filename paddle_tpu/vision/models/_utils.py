"""Shared vision-model building blocks."""
from __future__ import annotations

import os

from ... import nn


def load_pretrained(model, pretrained, arch=None):
    """The pretrained-weights story for the zoo factories
    (ref: load_dygraph_pretrain in python/paddle/vision/models/*.py).

    pretrained=False        -> random init, unchanged.
    pretrained='ckpt.pdparams' -> load the checkpoint into the model:
        both reference-framework .pdparams pickles (via compat) and
        paddle_tpu saves are sniffed and accepted; every parameter must
        match (strict — a partial load would silently mix random and
        pretrained weights).
    pretrained=True         -> loud gate: this offline environment has
        no download path; the error documents the convert-and-load
        recipe instead.

    `model` may be a zero-arg factory (the zoo passes `lambda: VGG(...)`)
    so the pretrained=True gate fires BEFORE paying model construction —
    vgg16's random init alone is ~18 s on a 1-core host."""
    def build():
        return model() if callable(model) and not isinstance(model, nn.Layer) \
            else model

    if not pretrained:
        return build()
    if isinstance(pretrained, (str, os.PathLike)):
        from ...serialization import load_into
        built = build()
        load_into(built, pretrained)
        return built
    name = arch or (type(model).__name__ if isinstance(model, nn.Layer)
                    else "Model")
    raise NotImplementedError(
        f"pretrained=True needs a weights download, which this offline "
        f"environment cannot do. Recipe: in the reference framework run "
        f"`paddle.save({name}(pretrained=True).state_dict(), "
        f"'{name}.pdparams')`, copy the file here, and pass "
        f"pretrained='{name}.pdparams' — reference .pdparams pickles "
        "load directly (see paddle_tpu.compat.load_pdparams)")


# back-compat alias: factories now pass the built model through
# load_pretrained; keep the old name importable
check_pretrained = load_pretrained


class ConvBNLayer(nn.Layer):
    """Conv2D + BatchNorm2D + optional activation — the block every conv
    net in the zoo repeats (ref: ConvBNLayer in each
    python/paddle/vision/models/*.py)."""

    _ACTS = {"relu": nn.ReLU, "relu6": nn.ReLU6, "hardswish": nn.Hardswish,
             "swish": nn.Swish, None: None}

    def __init__(self, in_c, out_c, k, stride=1, padding=0, groups=1,
                 act="relu"):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                              groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = self._ACTS[act]() if self._ACTS[act] else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act else x
