"""DenseNet (ref: python/paddle/vision/models/densenet.py)."""
from __future__ import annotations

from ...tensor_ops.manip import concat
from ... import nn
from ._utils import load_pretrained

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CFGS = {121: (64, 32, (6, 12, 24, 16)), 161: (96, 48, (6, 12, 36, 24)),
         169: (64, 32, (6, 12, 32, 32)), 201: (64, 32, (6, 12, 48, 32)),
         264: (64, 32, (6, 12, 64, 48))}


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_c)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout:
            out = self.dropout(out)
        return concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_c)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(in_c, out_c, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        assert layers in _CFGS, f"supported layers: {sorted(_CFGS)}"
        num_init, growth, blocks = _CFGS[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = nn.Conv2D(3, num_init, 7, stride=2, padding=3,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(num_init)
        self.relu = nn.ReLU()
        self.pool1 = nn.MaxPool2D(3, stride=2, padding=1)
        feats = []
        c = num_init
        for i, n in enumerate(blocks):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth, bn_size, dropout))
                c += growth
            if i != len(blocks) - 1:
                feats.append(_Transition(c, c // 2))
                c //= 2
        self.features = nn.Sequential(*feats)
        self.bn2 = nn.BatchNorm2D(c)
        if with_pool:
            self.pool2 = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.pool1(self.relu(self.bn1(self.conv1(x))))
        x = self.relu(self.bn2(self.features(x)))
        if self.with_pool:
            x = self.pool2(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def densenet121(pretrained=False, **kw):
    return load_pretrained(lambda: DenseNet(121, **kw), pretrained, arch="densenet121")


def densenet161(pretrained=False, **kw):
    return load_pretrained(lambda: DenseNet(161, **kw), pretrained, arch="densenet161")


def densenet169(pretrained=False, **kw):
    return load_pretrained(lambda: DenseNet(169, **kw), pretrained, arch="densenet169")


def densenet201(pretrained=False, **kw):
    return load_pretrained(lambda: DenseNet(201, **kw), pretrained, arch="densenet201")


def densenet264(pretrained=False, **kw):
    return load_pretrained(lambda: DenseNet(264, **kw), pretrained, arch="densenet264")
