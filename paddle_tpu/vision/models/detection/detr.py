"""DETR, TPU-native.

ref parity: PaddleDetection ppdet/modeling/architectures/detr.py
(transformer — ppdet/modeling/transformers/detr_transformer.py, matcher —
ppdet/modeling/transformers/matchers.py HungarianMatcher, loss —
ppdet/modeling/losses/detr_loss.py).

TPU-first redesign:

- **In-graph auction matcher.** The reference moves the cost matrix to CPU
  and calls scipy linear_sum_assignment per image — a host sync every step.
  Here bipartite matching runs ON the TPU as a Bertsekas auction
  (`auction_match`, lax.while_loop, static [Q, M] shapes, vmapped over the
  batch), eps-optimal with eps far below the cost quantization that matters
  for training.
- **Static padded gt** ([B, max_boxes] + mask) like ppyoloe; no dynamic
  shapes anywhere in the traced step.
- Positional/query embeddings are added once at the encoder/decoder inputs
  (the reference re-injects them at every attention layer; one-shot
  injection keeps the stock nn.Transformer usable and XLA fuses it all
  anyway).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ....nn import Layer, Linear, Conv2D, Embedding, Transformer, ReLU
from ....nn import functional as F
from ....tensor import Tensor
from ....autograd import apply_op
from ..resnet import resnet18, resnet50
from .box_utils import cxcywh_to_xyxy, pairwise_giou, elementwise_giou


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def sine_position_embedding(h, w, dim, temperature=10000.0):
    """2D sine embeddings [h*w, dim] (ref: position_encoding.py)."""
    half = dim // 2
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    freqs = temperature ** (2 * (np.arange(half // 2) // 1) / half)
    # interleave sin/cos over x and y halves
    def enc(v):
        v = v.reshape(-1)[:, None] / freqs[None, :]
        return np.concatenate([np.sin(v), np.cos(v)], -1)
    emb = np.concatenate([enc(ys), enc(xs)], -1)
    return jnp.asarray(emb.astype(np.float32))


def auction_match(cost, valid, eps=1e-3, max_iter=2000):
    """eps-optimal min-cost bipartite matching for ONE image, in-graph.

    cost [Q, M]: cost of assigning query q to gt m. valid [M] bool.
    Returns match [M] int32: the query index of each gt (arbitrary for
    invalid gts). Bertsekas auction (gts bid for queries), Jacobi variant:
    all unassigned gts bid each round, highest bid per query wins.
    """
    qn, m = cost.shape
    value = -cost  # auction maximizes
    big_neg = jnp.asarray(-1e9, value.dtype)

    def cond(state):
        it, price, owner, match = state
        unassigned = (match < 0) & valid
        return jnp.any(unassigned) & (it < max_iter)

    def body(state):
        it, price, owner, match = state
        unassigned = (match < 0) & valid                     # [M]
        net = value - price[:, None]                         # [Q, M]
        top2, top2_i = jax.lax.top_k(net.T, 2)               # [M, 2]
        best_q = top2_i[:, 0].astype(jnp.int32)
        bid = price[best_q] + (top2[:, 0] - top2[:, 1]) + eps  # [M]
        # scatter bids to queries; highest bidder per query wins
        bid_mat = jnp.where(
            (jax.nn.one_hot(best_q, qn, dtype=jnp.bool_).T
             & unassigned[None, :]),
            bid[None, :], big_neg)                           # [Q, M]
        win_bid = jnp.max(bid_mat, axis=1)                   # [Q]
        win_gt = jnp.argmax(bid_mat, axis=1).astype(jnp.int32)
        got_bid = win_bid > big_neg / 2
        # evict previous owners of re-auctioned queries
        match = jnp.where(
            (match >= 0) & got_bid[jnp.clip(match, 0, qn - 1)], -1, match)
        price = jnp.where(got_bid, win_bid, price)
        owner = jnp.where(got_bid, win_gt, owner)
        # winners take their queries
        match = jnp.where(
            unassigned
            & (jnp.take(owner, best_q) == jnp.arange(m))
            & jnp.take(got_bid, best_q),
            best_q, match)
        return it + 1, price, owner, match

    state = (jnp.int32(0),
             jnp.zeros((qn,), value.dtype),
             jnp.full((qn,), -1, jnp.int32),
             jnp.where(valid, -1, 0).astype(jnp.int32))
    _, _, _, match = jax.lax.while_loop(cond, body, state)
    return jnp.clip(match, 0, qn - 1)


class MLP(Layer):
    def __init__(self, in_dim, hidden, out_dim, n_layers=3):
        super().__init__()
        dims = [in_dim] + [hidden] * (n_layers - 1) + [out_dim]
        from ....nn import LayerList
        self.layers = LayerList([Linear(dims[i], dims[i + 1])
                                 for i in range(n_layers)])
        self.act = ReLU()

    def forward(self, x):
        for i, l in enumerate(self.layers):
            x = l(x)
            if i < len(self.layers) - 1:
                x = self.act(x)
        return x


class DETR(Layer):
    """ref: ppdet/modeling/architectures/detr.py.

    forward(images):
      train: (class_logits [B, Q, NC+1], pred_boxes [B, Q, 4] cxcywh in
      [0, 1]) — feed to DETRLoss.
      eval: (boxes_xyxy [B, Q, 4] in pixels, class_probs [B, Q, NC+1]).
    """

    def __init__(self, num_classes=80, num_queries=100, d_model=256,
                 nhead=8, num_encoder_layers=6, num_decoder_layers=6,
                 dim_feedforward=2048, backbone="resnet50", dropout=0.1):
        super().__init__()
        if backbone == "resnet50":
            self.backbone = resnet50(num_classes=0, with_pool=False)
            c_feat = 2048
        elif backbone == "resnet18":
            self.backbone = resnet18(num_classes=0, with_pool=False)
            c_feat = 512
        elif backbone == "tiny":  # 4-conv stride-16 stack for tests/smoke
            from ....nn import Sequential, BatchNorm2D, ReLU as _R
            c_feat = 64
            self.backbone = Sequential(
                Conv2D(3, 16, 3, stride=2, padding=1), BatchNorm2D(16), _R(),
                Conv2D(16, 32, 3, stride=2, padding=1), BatchNorm2D(32),
                _R(),
                Conv2D(32, 64, 3, stride=2, padding=1), BatchNorm2D(64),
                _R(),
                Conv2D(64, c_feat, 3, stride=2, padding=1),
                BatchNorm2D(c_feat), _R())
        else:
            raise ValueError(
                f"unknown backbone {backbone!r}; expected 'resnet50', "
                "'resnet18' or 'tiny'")
        self.input_proj = Conv2D(c_feat, d_model, 1)
        self.transformer = Transformer(
            d_model, nhead, num_encoder_layers, num_decoder_layers,
            dim_feedforward, dropout)
        self.query_embed = Embedding(num_queries, d_model)
        self.class_head = Linear(d_model, num_classes + 1)
        self.bbox_head = MLP(d_model, d_model, 4)
        self.num_queries = num_queries
        self.num_classes = num_classes
        self.d_model = d_model

    def forward(self, images):
        feat = self.input_proj(self.backbone(images))      # [B, D, H, W]
        b, d, h, w = feat.shape
        src = feat.reshape([b, d, h * w]).transpose([0, 2, 1])
        pos = sine_position_embedding(h, w, d)
        src = apply_op(lambda s, p: s + p[None], _t(src), _t(pos))
        queries = self.query_embed.weight                  # [Q, D]
        tgt = apply_op(
            lambda q, bsz=b: jnp.broadcast_to(q[None], (bsz,) + q.shape),
            _t(queries))
        hs = self.transformer(src, tgt)                    # [B, Q, D]
        logits = self.class_head(hs)
        boxes = F.sigmoid(self.bbox_head(hs))              # cxcywh in [0,1]
        if self.training:
            return logits, boxes
        ih, iw = images.shape[2], images.shape[3]
        scale = jnp.asarray([iw, ih, iw, ih], jnp.float32)
        out_boxes = apply_op(
            lambda bx: cxcywh_to_xyxy(bx) * scale, _t(boxes))
        probs = F.softmax(logits, axis=-1)
        return out_boxes, probs


class DETRLoss(Layer):
    """Hungarian set loss: CE (eos-weighted) + L1 + GIoU on matched pairs
    (ref: ppdet/modeling/losses/detr_loss.py). labels = (gt_boxes
    [B, M, 4] cxcywh normalized, gt_class [B, M], gt_mask [B, M])."""

    def __init__(self, num_classes, eos_coef=0.1,
                 w_class=1.0, w_l1=5.0, w_giou=2.0,
                 cost_class=1.0, cost_l1=5.0, cost_giou=2.0):
        super().__init__()
        self.num_classes = num_classes
        self.eos_coef = eos_coef
        self.w = (w_class, w_l1, w_giou)
        self.cost_w = (cost_class, cost_l1, cost_giou)

    def forward(self, logits, boxes, gt_boxes, gt_class, gt_mask):
        args = [_t(a) for a in (logits, boxes, gt_boxes, gt_class, gt_mask)]
        nc = self.num_classes
        eos = self.eos_coef
        wc, wl, wg = self.w
        cc, cl, cg = self.cost_w

        def one_image(lg, bx, gb, gc, gm):
            # cost matrix [Q, M]
            prob = jax.nn.softmax(lg, -1)
            c_cls = -prob[:, gc]                            # [Q, M]
            c_l1 = jnp.abs(bx[:, None, :] - gb[None, :, :]).sum(-1)
            c_giou = -pairwise_giou(cxcywh_to_xyxy(bx), cxcywh_to_xyxy(gb))
            cost = cc * c_cls + cl * c_l1 + cg * c_giou
            match = auction_match(jax.lax.stop_gradient(cost), gm > 0)

            # classification: every query predicts no-object unless matched
            # (padded gts scatter to an out-of-range index -> dropped, so
            # they can never clobber a real match)
            mvalid = gm > 0
            tgt_cls = jnp.full((lg.shape[0],), nc, jnp.int32)
            idx = jnp.where(mvalid, match, lg.shape[0])
            tgt_cls = tgt_cls.at[idx].set(gc, mode="drop")
            logp = jax.nn.log_softmax(lg, -1)
            ce = -jnp.take_along_axis(logp, tgt_cls[:, None], 1)[:, 0]
            w_ce = jnp.where(tgt_cls == nc, eos, 1.0)
            l_cls = jnp.sum(ce * w_ce) / jnp.sum(w_ce)

            # box losses on matched pairs
            mb = bx[match]                                  # [M, 4]
            l_l1 = jnp.sum(jnp.abs(mb - gb).sum(-1) * mvalid)
            gi = elementwise_giou(cxcywh_to_xyxy(mb), cxcywh_to_xyxy(gb))
            l_giou = jnp.sum((1.0 - gi) * mvalid)
            n = jnp.maximum(jnp.sum(mvalid), 1.0)
            return wc * l_cls + (wl * l_l1 + wg * l_giou) / n

        def f(logits, boxes, gt_boxes, gt_class, gt_mask):
            per_img = jax.vmap(one_image)(
                logits, boxes, gt_boxes, gt_class.astype(jnp.int32),
                gt_mask)
            return per_img.mean()
        return apply_op(f, *args)
