"""PP-YOLOE, TPU-native.

ref parity: PaddleDetection ppdet/modeling/architectures/ppyoloe.py
(CSPResNet backbone — ppdet/modeling/backbones/cspresnet.py, CustomCSPPAN
neck — ppdet/modeling/necks/custom_pan.py, PPYOLOEHead with ET-head +
TAL assigner — ppdet/modeling/heads/ppyoloe_head.py,
ppdet/modeling/assigners/task_aligned_assigner.py).

TPU-first redesign of the parts that are dynamic in the reference:

- **Static shapes everywhere.** Ground truth comes padded to `max_boxes`
  with a validity mask; the task-aligned assigner is pure matmul/top_k
  tensor algebra over the fixed [anchors, max_boxes] grid (the reference
  uses gather/scatter over per-image variable-length gt lists).
- **No NMS in-graph.** Training never needs it; eval returns decoded
  boxes + scores and `multiclass_nms` (numpy, host-side) finishes
  postprocessing — keeping every traced program free of dynamic shapes.
- **vmap over the batch** instead of per-image Python loops, so XLA sees
  one fused batched assignment.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ....nn import (BatchNorm2D, Conv2D, Layer, LayerList, Sequential, Silu)
from ....nn import functional as F
from ....tensor import Tensor
from ....tensor_ops.manip import concat
from ....autograd import apply_op
from .box_utils import pairwise_iou, elementwise_giou


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


class ConvBNLayer(Layer):
    def __init__(self, ch_in, ch_out, k=3, stride=1, groups=1, padding=None,
                 act=True):
        super().__init__()
        if padding is None:
            padding = (k - 1) // 2
        self.conv = Conv2D(ch_in, ch_out, k, stride=stride, padding=padding,
                           groups=groups, bias_attr=False)
        self.bn = BatchNorm2D(ch_out)
        self.act = Silu() if act else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act is not None else x


class EffectiveSELayer(Layer):
    """ESE attention (ref: cspresnet.py EffectiveSELayer)."""

    def __init__(self, channels):
        super().__init__()
        self.fc = Conv2D(channels, channels, 1)

    def forward(self, x):
        w = x.mean(axis=[2, 3], keepdim=True)
        w = self.fc(w)
        return apply_op(lambda a, b: a * jax.nn.hard_sigmoid(b),
                        _t(x), _t(w))


class RepVggBlock(Layer):
    """Training-form RepVGG block: 3x3 + 1x1 branches summed (the deploy
    re-parameterized single conv is an inference-only transform; XLA fuses
    the two branches anyway)."""

    def __init__(self, ch_in, ch_out):
        super().__init__()
        self.conv1 = ConvBNLayer(ch_in, ch_out, 3, act=False)
        self.conv2 = ConvBNLayer(ch_in, ch_out, 1, act=False)
        self.act = Silu()

    def forward(self, x):
        return self.act(self.conv1(x) + self.conv2(x))


class CSPResBlock(Layer):
    def __init__(self, ch, shortcut=True):
        super().__init__()
        self.conv1 = ConvBNLayer(ch, ch, 3)
        self.conv2 = RepVggBlock(ch, ch)
        self.shortcut = shortcut

    def forward(self, x):
        y = self.conv2(self.conv1(x))
        return x + y if self.shortcut else y


class CSPResStage(Layer):
    def __init__(self, ch_in, ch_out, n, stride=2, use_attn=True):
        super().__init__()
        ch_mid = (ch_in + ch_out) // 2
        self.conv_down = (ConvBNLayer(ch_in, ch_mid, 3, stride=stride)
                          if stride > 1 else None)
        half = ch_mid // 2
        self.conv1 = ConvBNLayer(ch_mid if stride > 1 else ch_in, half, 1)
        self.conv2 = ConvBNLayer(ch_mid if stride > 1 else ch_in, half, 1)
        self.blocks = Sequential(*[CSPResBlock(half) for _ in range(n)])
        self.attn = EffectiveSELayer(2 * half) if use_attn else None
        self.conv3 = ConvBNLayer(2 * half, ch_out, 1)

    def forward(self, x):
        if self.conv_down is not None:
            x = self.conv_down(x)
        y1 = self.conv1(x)
        y2 = self.blocks(self.conv2(x))
        y = concat([y1, y2], axis=1)
        if self.attn is not None:
            y = self.attn(y)
        return self.conv3(y)


class CSPResNet(Layer):
    """ref: ppdet/modeling/backbones/cspresnet.py."""

    def __init__(self, layers=(1, 1, 1, 1), channels=(32, 64, 128, 256, 512),
                 return_idx=(1, 2, 3)):
        super().__init__()
        self.return_idx = tuple(return_idx)
        c = list(channels)
        self.stem = Sequential(
            ConvBNLayer(3, c[0] // 2, 3, stride=2),
            ConvBNLayer(c[0] // 2, c[0], 3, stride=1),
        )
        self.stages = LayerList([
            CSPResStage(c[i], c[i + 1], layers[i], stride=2)
            for i in range(len(layers))
        ])
        self.out_channels = [c[i + 1] for i in self.return_idx]
        # stem stride 2, each stage stride 2: stage i sits at stride 2^(i+2)
        # -> return_idx (1,2,3) = strides (8, 16, 32), the reference's heads
        self.out_strides = [2 ** (i + 2) for i in self.return_idx]

    def forward(self, x):
        x = self.stem(x)
        outs = []
        for i, st in enumerate(self.stages):
            x = st(x)
            if i in self.return_idx:
                outs.append(x)
        return outs


class CustomCSPPAN(Layer):
    """PAN neck: top-down FPN + bottom-up path, CSP fuse stages
    (ref: ppdet/modeling/necks/custom_pan.py)."""

    def __init__(self, in_channels, out_channels=None):
        super().__init__()
        n = len(in_channels)
        out_channels = out_channels or in_channels
        self.lateral = LayerList([
            ConvBNLayer(in_channels[i], out_channels[i], 1)
            for i in range(n)])
        self.fpn_blocks = LayerList([
            CSPResStage(out_channels[i] + out_channels[i + 1],
                        out_channels[i], 1, stride=1, use_attn=False)
            for i in range(n - 1)])
        self.down_convs = LayerList([
            ConvBNLayer(out_channels[i], out_channels[i], 3, stride=2)
            for i in range(n - 1)])
        self.pan_blocks = LayerList([
            CSPResStage(out_channels[i] + out_channels[i + 1],
                        out_channels[i + 1], 1, stride=1, use_attn=False)
            for i in range(n - 1)])
        self.out_channels = list(out_channels)

    def forward(self, feats):
        lat = [l(f) for l, f in zip(self.lateral, feats)]
        # top-down
        for i in range(len(lat) - 2, -1, -1):
            up = F.interpolate(lat[i + 1], scale_factor=2, mode="nearest")
            lat[i] = self.fpn_blocks[i](concat([lat[i], up], axis=1))
        # bottom-up
        for i in range(len(lat) - 1):
            down = self.down_convs[i](lat[i])
            lat[i + 1] = self.pan_blocks[i](
                concat([down, lat[i + 1]], axis=1))
        return lat


class ESEHead(Layer):
    """One ET-head branch: ESE attention + conv stem."""

    def __init__(self, ch):
        super().__init__()
        self.attn = EffectiveSELayer(ch)
        self.conv = ConvBNLayer(ch, ch, 3)

    def forward(self, x):
        return self.conv(self.attn(x)) + x


def _anchor_points(sizes, strides):
    """Static anchor centers for all levels: [A, 2] (x, y) in pixels and
    [A] stride."""
    pts, strs = [], []
    for (h, w), s in zip(sizes, strides):
        ys = (np.arange(h) + 0.5) * s
        xs = (np.arange(w) + 0.5) * s
        gx, gy = np.meshgrid(xs, ys)
        pts.append(np.stack([gx.reshape(-1), gy.reshape(-1)], -1))
        strs.append(np.full((h * w,), s, np.float32))
    return (jnp.asarray(np.concatenate(pts).astype(np.float32)),
            jnp.asarray(np.concatenate(strs)))


def task_aligned_assign(pred_scores, pred_boxes, anchors, gt_boxes, gt_class,
                        gt_mask, alpha=1.0, beta=6.0, topk=13):
    """TAL for ONE image, fully static (ref: task_aligned_assigner.py).

    pred_scores [A, NC] (sigmoid), pred_boxes [A, 4] xyxy, anchors [A, 2],
    gt_boxes [M, 4], gt_class [M] int, gt_mask [M] {0,1}.
    Returns (assigned_gt [A] int, fg_mask [A], target_score [A, NC]).
    """
    a = anchors.shape[0]
    m = gt_boxes.shape[0]
    iou, _ = pairwise_iou(pred_boxes, gt_boxes)          # [A, M]
    cls = jnp.take_along_axis(
        pred_scores, jnp.broadcast_to(gt_class[None, :], (a, m)), axis=1)
    metric = (cls ** alpha) * (iou ** beta)              # [A, M]

    # candidate anchors: center inside gt box
    inside = ((anchors[:, None, 0] >= gt_boxes[None, :, 0])
              & (anchors[:, None, 0] <= gt_boxes[None, :, 2])
              & (anchors[:, None, 1] >= gt_boxes[None, :, 1])
              & (anchors[:, None, 1] <= gt_boxes[None, :, 3]))
    valid = inside & (gt_mask[None, :] > 0)
    metric = jnp.where(valid, metric, 0.0)

    # top-k anchors per gt (static top_k over the anchor axis)
    k = min(topk, a)
    thresh = jax.lax.top_k(metric.T, k)[0][:, -1]        # [M] k-th metric
    is_topk = (metric >= jnp.maximum(thresh, 1e-9)[None, :]) & valid

    cand = jnp.where(is_topk, metric, 0.0)
    # conflict resolution: anchor goes to the gt with max metric
    assigned = jnp.argmax(cand, axis=1)                  # [A]
    best = jnp.max(cand, axis=1)
    fg = best > 0.0

    # normalized target score (TAL: metric / max_metric * max_iou per gt)
    max_metric = jnp.max(cand, axis=0)                   # [M]
    max_iou = jnp.max(jnp.where(is_topk, iou, 0.0), axis=0)
    norm = jnp.where(max_metric > 0, max_iou / (max_metric + 1e-9), 0.0)
    t = best * norm[assigned]                            # [A]
    nc = pred_scores.shape[1]
    target_score = (jax.nn.one_hot(gt_class[assigned], nc) * t[:, None]
                    * fg[:, None])
    return assigned, fg, target_score


class PPYOLOEHead(Layer):
    """ET-head: decoupled cls/reg with ESE attention + DFL regression
    (ref: ppdet/modeling/heads/ppyoloe_head.py)."""

    def __init__(self, in_channels, num_classes=80, reg_max=16,
                 strides=(8, 16, 32)):
        super().__init__()
        self.num_classes = num_classes
        self.reg_max = reg_max
        self.strides = list(strides)
        self.stem_cls = LayerList([ESEHead(c) for c in in_channels])
        self.stem_reg = LayerList([ESEHead(c) for c in in_channels])
        self.pred_cls = LayerList([
            Conv2D(c, num_classes, 3, padding=1) for c in in_channels])
        self.pred_reg = LayerList([
            Conv2D(c, 4 * (reg_max + 1), 3, padding=1) for c in in_channels])
        # dfl projection (expectation over the discretized distribution)
        self.proj = jnp.arange(reg_max + 1, dtype=jnp.float32)

    def forward(self, feats):
        """Returns (cls_logits [B, A, NC], reg_dist [B, A, 4, reg_max+1],
        sizes [(h, w)...])."""
        cls_out, reg_out, sizes = [], [], []
        for i, f in enumerate(feats):
            c = self.pred_cls[i](self.stem_cls[i](f))
            r = self.pred_reg[i](self.stem_reg[i](f))
            b, _, h, w = c.shape
            sizes.append((h, w))
            cls_out.append(c.reshape([b, self.num_classes, h * w])
                           .transpose([0, 2, 1]))
            reg_out.append(r.reshape([b, 4, self.reg_max + 1, h * w])
                           .transpose([0, 3, 1, 2]))
        return (concat(cls_out, axis=1), concat(reg_out, axis=1), sizes)

    def decode_boxes(self, reg_dist, anchors, strides):
        """DFL expectation -> ltrb distances -> xyxy boxes."""
        def f(rd):
            dist = jax.nn.softmax(rd, axis=-1) @ self.proj   # [B, A, 4]
            dist = dist * strides[None, :, None]
            x0 = anchors[None, :, 0] - dist[..., 0]
            y0 = anchors[None, :, 1] - dist[..., 1]
            x1 = anchors[None, :, 0] + dist[..., 2]
            y1 = anchors[None, :, 1] + dist[..., 3]
            return jnp.stack([x0, y0, x1, y1], -1)
        return apply_op(f, _t(reg_dist))


class PPYOLOELoss(Layer):
    """VFL + GIoU + DFL with TAL assignment
    (ref: ppyoloe_head.py get_loss)."""

    def __init__(self, num_classes=80, reg_max=16,
                 w_cls=1.0, w_iou=2.5, w_dfl=0.5):
        super().__init__()
        self.num_classes = num_classes
        self.reg_max = reg_max
        self.w = (w_cls, w_iou, w_dfl)

    def forward(self, cls_logits, pred_boxes, reg_dist, anchors, strides,
                gt_boxes, gt_class, gt_mask):
        args = [_t(a) for a in (cls_logits, pred_boxes, reg_dist, gt_boxes,
                                gt_class, gt_mask)]

        def f(cls_logits, pred_boxes, reg_dist, gt_boxes, gt_class, gt_mask):
            scores = jax.nn.sigmoid(cls_logits)

            assign = jax.vmap(
                lambda s, b, gb, gc, gm: task_aligned_assign(
                    s, b, anchors, gb, gc, gm))
            assigned, fg, tscore = assign(
                scores, jax.lax.stop_gradient(pred_boxes),
                gt_boxes, gt_class.astype(jnp.int32), gt_mask)

            # varifocal loss (IoU-aware cls target)
            q = tscore
            p = scores
            w_vfl = jnp.where(q > 0, q, 0.75 * (p ** 2))
            bce = -(q * jax.nn.log_sigmoid(cls_logits)
                    + (1 - q) * jax.nn.log_sigmoid(-cls_logits))
            n_pos = jnp.maximum(jnp.sum(tscore), 1.0)
            l_cls = jnp.sum(w_vfl * bce) / n_pos

            # box losses on fg anchors
            tgt_box = jnp.take_along_axis(
                gt_boxes, assigned[..., None].repeat(4, -1), axis=1)
            giou = elementwise_giou(pred_boxes, tgt_box)
            wt = jnp.sum(tscore, -1) * fg
            l_iou = jnp.sum((1.0 - giou) * wt) / n_pos

            # dfl: distances in stride units, left/right CE
            def ltrb(boxes):
                l = (anchors[None, :, 0] - boxes[..., 0]) / strides[None, :]
                t = (anchors[None, :, 1] - boxes[..., 1]) / strides[None, :]
                r = (boxes[..., 2] - anchors[None, :, 0]) / strides[None, :]
                b = (boxes[..., 3] - anchors[None, :, 1]) / strides[None, :]
                return jnp.stack([l, t, r, b], -1)
            tdist = jnp.clip(ltrb(tgt_box), 0, self.reg_max - 0.01)
            tl = jnp.floor(tdist)
            wl = tl + 1.0 - tdist
            logp = jax.nn.log_softmax(reg_dist, axis=-1)
            li = tl.astype(jnp.int32)
            take = lambda idx: jnp.take_along_axis(
                logp, idx[..., None], axis=-1)[..., 0]
            ce = -(take(li) * wl + take(li + 1) * (1.0 - wl))
            l_dfl = jnp.sum(ce.mean(-1) * wt) / n_pos

            wc, wi, wd = self.w
            return wc * l_cls + wi * l_iou + wd * l_dfl
        return apply_op(f, *args)


class PPYOLOE(Layer):
    """Full architecture (ref: ppdet/modeling/architectures/ppyoloe.py).

    Train: forward(images) -> dict of raw predictions; pair with
    PPYOLOECriterion for the loss.
    Eval: forward(images) -> (boxes [B, A, 4], scores [B, A, NC]); finish
    with `multiclass_nms` on host.
    """

    def __init__(self, num_classes=80, layers=(1, 1, 1, 1),
                 channels=(32, 64, 128, 256, 512), reg_max=16):
        super().__init__()
        self.backbone = CSPResNet(layers, channels)
        self.neck = CustomCSPPAN(self.backbone.out_channels)
        self.head = PPYOLOEHead(self.neck.out_channels, num_classes,
                                reg_max, strides=self.backbone.out_strides)
        self.num_classes = num_classes

    def _predict(self, images):
        feats = self.neck(self.backbone(images))
        cls_logits, reg_dist, sizes = self.head(feats)
        anchors, strides = _anchor_points(sizes, self.head.strides)
        # anchors are trace-time constants (derived from static feature
        # sizes); stash them for the criterion, which runs in the same trace
        self._last_anchors = (anchors, strides)
        boxes = self.head.decode_boxes(reg_dist, anchors, strides)
        return cls_logits, reg_dist, boxes, anchors, strides

    def forward(self, images):
        cls_logits, reg_dist, boxes, anchors, strides = self._predict(images)
        if self.training:
            return cls_logits, reg_dist, boxes
        scores = F.sigmoid(cls_logits)
        return boxes, scores


class PPYOLOECriterion(Layer):
    """Adapter so Engine/Model can drive PPYOLOE: loss(outputs..., labels...)
    where labels = (gt_boxes [B, M, 4], gt_class [B, M], gt_mask [B, M])."""

    def __init__(self, model: PPYOLOE):
        super().__init__()
        self.loss = PPYOLOELoss(model.num_classes, model.head.reg_max)
        self._model = [model]  # not a sublayer: avoid double registration

    def forward(self, cls_logits, reg_dist, boxes, gt_boxes, gt_class,
                gt_mask):
        model = self._model[0]
        # anchors depend only on static sizes; recompute from reg shape via
        # cached head config (strides fixed, sizes from the train images)
        anchors, strides = model._last_anchors
        return self.loss(cls_logits, boxes, reg_dist, anchors, strides,
                         gt_boxes, gt_class, gt_mask)


def multiclass_nms(boxes, scores, score_thresh=0.05, iou_thresh=0.6,
                   max_dets=100):
    """Host-side NMS (numpy) — the reference runs NMS inside the graph on
    GPU (ppdet multiclass_nms op); on TPU dynamic-shape NMS would break XLA
    so it lives in postprocess. boxes [A, 4], scores [A, NC]."""
    boxes = np.asarray(boxes)
    scores = np.asarray(scores)
    out = []
    for c in range(scores.shape[1]):
        s = scores[:, c]
        keep = s > score_thresh
        b, s = boxes[keep], s[keep]
        order = np.argsort(-s)
        b, s = b[order], s[order]
        while len(b):
            out.append((c, float(s[0]), b[0]))
            if len(b) == 1:
                break
            x0 = np.maximum(b[0, 0], b[1:, 0])
            y0 = np.maximum(b[0, 1], b[1:, 1])
            x1 = np.minimum(b[0, 2], b[1:, 2])
            y1 = np.minimum(b[0, 3], b[1:, 3])
            inter = np.clip(x1 - x0, 0, None) * np.clip(y1 - y0, 0, None)
            area0 = (b[0, 2] - b[0, 0]) * (b[0, 3] - b[0, 1])
            area = (b[1:, 2] - b[1:, 0]) * (b[1:, 3] - b[1:, 1])
            iou = inter / (area0 + area - inter + 1e-9)
            keep_rest = iou <= iou_thresh
            b, s = b[1:][keep_rest], s[1:][keep_rest]
    out.sort(key=lambda r: -r[1])
    return out[:max_dets]
