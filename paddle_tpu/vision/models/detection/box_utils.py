"""Box utilities (ref: ppdet/modeling/bbox_utils.py)."""
from __future__ import annotations

import jax.numpy as jnp


def cxcywh_to_xyxy(b):
    cx, cy, w, h = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


def xyxy_to_cxcywh(b):
    x0, y0, x1, y1 = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack([(x0 + x1) / 2, (y0 + y1) / 2, x1 - x0, y1 - y0],
                     axis=-1)


def box_area(b):
    return (b[..., 2] - b[..., 0]).clip(0) * (b[..., 3] - b[..., 1]).clip(0)


def pairwise_iou(a, b):
    """a [N, 4], b [M, 4] xyxy -> iou [N, M] (+ union for giou)."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = (rb - lt).clip(0)
    inter = wh[..., 0] * wh[..., 1]
    union = box_area(a)[:, None] + box_area(b)[None, :] - inter
    return inter / (union + 1e-9), union


def pairwise_giou(a, b):
    iou, union = pairwise_iou(a, b)
    lt = jnp.minimum(a[:, None, :2], b[None, :, :2])
    rb = jnp.maximum(a[:, None, 2:], b[None, :, 2:])
    wh = (rb - lt).clip(0)
    hull = wh[..., 0] * wh[..., 1]
    return iou - (hull - union) / (hull + 1e-9)


def elementwise_giou(a, b):
    """a, b [..., 4] xyxy aligned."""
    lt = jnp.maximum(a[..., :2], b[..., :2])
    rb = jnp.minimum(a[..., 2:], b[..., 2:])
    wh = (rb - lt).clip(0)
    inter = wh[..., 0] * wh[..., 1]
    union = box_area(a) + box_area(b) - inter
    iou = inter / (union + 1e-9)
    lt_h = jnp.minimum(a[..., :2], b[..., :2])
    rb_h = jnp.maximum(a[..., 2:], b[..., 2:])
    wh_h = (rb_h - lt_h).clip(0)
    hull = wh_h[..., 0] * wh_h[..., 1]
    return iou - (hull - union) / (hull + 1e-9)
