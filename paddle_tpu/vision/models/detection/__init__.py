"""Detection zoo (ref: PaddleDetection ppdet/modeling)."""
from .box_utils import (  # noqa: F401
    cxcywh_to_xyxy, xyxy_to_cxcywh, box_area, pairwise_iou, pairwise_giou,
    elementwise_giou,
)
from .ppyoloe import (  # noqa: F401
    PPYOLOE, PPYOLOECriterion, PPYOLOELoss, CSPResNet, CustomCSPPAN,
    PPYOLOEHead, task_aligned_assign, multiclass_nms,
)
from .detr import (  # noqa: F401
    DETR, DETRLoss, auction_match, sine_position_embedding,
)
