"""paddle.vision.ops parity (ref: python/paddle/vision/ops.py).

TPU-first designs:
- `nms`: iterative greedy NMS is O(N) sequential host logic on GPU; here it
  is a fixed-trip-count `lax.fori_loop` over a precomputed [N, N] IoU
  matrix — one matmul-shaped batch of comparisons, static shapes, jittable.
- `roi_align`: expressed as a bilinear-gather + mean over a static sampling
  grid, vectorized over rois — no per-roi dynamic loops.
- `deform_conv2d`: sample-then-matmul (gather the deformed patches, one
  einsum against the kernel), the standard TPU formulation for deformable
  conv since dynamic scatter/gather convs don't exist in XLA.
- `distribute_fpn_proposals` returns static-shape per-level masks instead
  of ragged per-level lists (documented divergence: XLA has no ragged
  outputs; callers mask instead of gather).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd import apply_op
from ..tensor import Tensor, to_tensor
from ..nn.layer import Layer

__all__ = [
    "nms", "box_iou", "roi_align", "roi_pool", "box_coder", "yolo_box",
    "distribute_fpn_proposals", "deform_conv2d", "DeformConv2D", "PSRoIPool",
    "RoIAlign", "RoIPool",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _arr(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


# ---------------------------------------------------------------------------
# box ops
# ---------------------------------------------------------------------------
def box_iou(boxes1, boxes2, name=None):
    """ref: paddle.vision.ops.box_iou — [N,4] x [M,4] xyxy -> [N,M]."""
    from .models.detection.box_utils import pairwise_iou

    def f(a, b):
        iou, _ = pairwise_iou(a, b)
        return iou
    return apply_op(f, _t(boxes1), _t(boxes2))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """ref: paddle.vision.ops.nms.

    Greedy NMS as a static-shape `fori_loop`: at step i the highest-scored
    surviving box is selected and every box with IoU > threshold against it
    is suppressed. Returns kept indices sorted by score (dynamic length on
    the host; inside jit use the returned mask form via `top_k`).

    With `top_k=None` the call is a host-facing API (returns a variable-
    length index Tensor). With `top_k=k` the result is the fixed-shape
    first-k kept indices (padded with -1) — the jit-safe form.
    """
    b = _arr(_t(boxes)).astype(jnp.float32)
    n = b.shape[0]
    s = (jnp.arange(n, 0, -1, dtype=jnp.float32) if scores is None
         else _arr(_t(scores)).astype(jnp.float32))

    if category_idxs is not None:
        # category-aware: offset boxes per category so cross-category pairs
        # never overlap (the standard batched-NMS trick)
        cidx = _arr(_t(category_idxs)).astype(jnp.float32)
        span = jnp.max(b) - jnp.min(b) + 1.0
        b = b + (cidx * span)[:, None]

    from .models.detection.box_utils import pairwise_iou
    iou, _ = pairwise_iou(b, b)

    # sort by score; greedy NMS becomes: keep[j] unless some KEPT i<j
    # overlaps it. The only sequential dependency is the keep vector — a
    # fori_loop over one precomputed [N, N] bool matrix (no per-step IoU
    # kernels, unlike the GPU reference's atomic bitmask walk)
    order = jnp.argsort(-s)
    inv = jnp.argsort(order)
    iou_sorted = iou[order][:, order]
    tri = jnp.tril(iou_sorted > iou_threshold, k=-1)  # j vs any i<j

    def loop_body(j, keep):
        suppressed = jnp.any(tri[j] & keep)
        return keep.at[j].set(~suppressed)

    keep_sorted = jax.lax.fori_loop(0, n, loop_body, jnp.zeros((n,), bool))
    keep = keep_sorted[inv]

    if top_k is not None:
        k = int(top_k)
        score_keep = jnp.where(keep, s, -jnp.inf)
        idx = jnp.argsort(-score_keep)[:k]
        valid = keep[idx]
        return Tensor(jnp.where(valid, idx, -1).astype(jnp.int64))
    # host-facing: variable-length kept indices sorted by score
    keep_np = np.asarray(keep)
    s_np = np.asarray(s)
    kept = np.nonzero(keep_np)[0]
    kept = kept[np.argsort(-s_np[kept])]
    return Tensor(jnp.asarray(kept, dtype=jnp.int64))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """ref: paddle.vision.ops.box_coder (encode/decode center-size)."""
    pb = _arr(_t(prior_box)).astype(jnp.float32)
    pbv = (jnp.asarray(prior_box_var, jnp.float32)
           if not isinstance(prior_box_var, (Tensor,))
           else _arr(prior_box_var).astype(jnp.float32))
    norm = 0.0 if box_normalized else 1.0

    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph / 2

    if code_type == "encode_center_size":
        def f(tb):
            tw = tb[:, None, 2] - tb[:, None, 0] + norm
            th = tb[:, None, 3] - tb[:, None, 1] + norm
            tcx = tb[:, None, 0] + tw / 2
            tcy = tb[:, None, 1] + th / 2
            out = jnp.stack([
                (tcx - pcx[None]) / pw[None],
                (tcy - pcy[None]) / ph[None],
                jnp.log(tw / pw[None]),
                jnp.log(th / ph[None]),
            ], -1)
            return out / jnp.reshape(pbv, (1, -1, 4) if pbv.ndim == 2
                                     else (1, 1, 4))
        return apply_op(f, _t(target_box))

    if code_type == "decode_center_size":
        def f(tb):
            v = pbv if pbv.ndim == 2 else jnp.broadcast_to(
                jnp.reshape(pbv, (1, 4)), pb.shape)
            if axis == 0:
                prior = (pcx[None, :], pcy[None, :], pw[None, :], ph[None, :])
                var = v[None, :, :]
            else:
                prior = (pcx[:, None], pcy[:, None], pw[:, None], ph[:, None])
                var = v[:, None, :]
            dcx = var[..., 0] * tb[..., 0] * prior[2] + prior[0]
            dcy = var[..., 1] * tb[..., 1] * prior[3] + prior[1]
            dw = jnp.exp(var[..., 2] * tb[..., 2]) * prior[2]
            dh = jnp.exp(var[..., 3] * tb[..., 3]) * prior[3]
            return jnp.stack([dcx - dw / 2, dcy - dh / 2,
                              dcx + dw / 2 - norm, dcy + dh / 2 - norm], -1)
        return apply_op(f, _t(target_box))

    raise ValueError(f"unknown code_type {code_type!r}")


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5, name=None):
    """ref: paddle.vision.ops.yolo_box — decode YOLO head predictions.

    x: [B, na*(5+C), H, W]; returns (boxes [B, H*W*na, 4],
    scores [B, H*W*na, C]). Low-confidence boxes are zeroed (static shape),
    matching the reference's behavior of zero-filling below conf_thresh.
    """
    na = len(anchors) // 2
    anc = jnp.asarray(np.asarray(anchors, np.float32).reshape(na, 2))
    imgs = _arr(_t(img_size)).astype(jnp.float32)  # [B, 2] (h, w)

    def f(xv):
        b, _, h, w = xv.shape
        if iou_aware:
            # iou-aware head layout: the first na channels are IoU
            # predictions, then the standard na*(5+C) block
            iou_p = jax.nn.sigmoid(xv[:, :na].reshape(b, na, h, w))
            v = xv[:, na:].reshape(b, na, 5 + class_num, h, w)
        else:
            v = xv.reshape(b, na, 5 + class_num, h, w)
        gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
        sig = jax.nn.sigmoid
        alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)
        cx = (sig(v[:, :, 0]) * alpha + beta + gx) / w
        cy = (sig(v[:, :, 1]) * alpha + beta + gy) / h
        in_w, in_h = w * downsample_ratio, h * downsample_ratio
        bw = jnp.exp(v[:, :, 2]) * anc[None, :, 0, None, None] / in_w
        bh = jnp.exp(v[:, :, 3]) * anc[None, :, 1, None, None] / in_h
        obj = sig(v[:, :, 4])
        if iou_aware:
            # conf = obj^(1-f) * iou^f
            f_ = iou_aware_factor
            obj = jnp.power(obj, 1.0 - f_) * jnp.power(iou_p, f_)
        cls = sig(v[:, :, 5:])  # [B, na, C, H, W]
        conf = obj[:, :, None] * cls
        # to pixel coords per image
        imw = imgs[:, 1][:, None, None, None]
        imh = imgs[:, 0][:, None, None, None]
        x0 = (cx - bw / 2) * imw
        y0 = (cy - bh / 2) * imh
        x1 = (cx + bw / 2) * imw
        y1 = (cy + bh / 2) * imh
        if clip_bbox:
            x0 = jnp.clip(x0, 0, imw - 1)
            y0 = jnp.clip(y0, 0, imh - 1)
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
        boxes = jnp.stack([x0, y0, x1, y1], -1)        # [B,na,H,W,4]
        keep = (obj > conf_thresh)[..., None]
        boxes = jnp.where(keep, boxes, 0.0)
        conf = jnp.moveaxis(conf, 2, -1)               # [B,na,H,W,C]
        conf = jnp.where(keep, conf, 0.0)
        return (boxes.reshape(b, -1, 4),
                conf.reshape(b, -1, class_num))

    return apply_op(f, _t(x))


# ---------------------------------------------------------------------------
# roi ops
# ---------------------------------------------------------------------------
def _bilinear_gather(feat, ys, xs):
    """feat [C, H, W]; ys/xs [...] float coords -> [C, ...]."""
    h, w = feat.shape[-2:]
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    y1, x1 = y0 + 1, x0 + 1
    wy1 = ys - y0
    wx1 = xs - x0
    wy0, wx0 = 1 - wy1, 1 - wx1

    def g(yy, xx):
        yi = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xi = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        return feat[:, yi, xi]

    out = (g(y0, x0) * (wy0 * wx0) + g(y0, x1) * (wy0 * wx1)
           + g(y1, x0) * (wy1 * wx0) + g(y1, x1) * (wy1 * wx1))
    # zero outside [-1, H/W] like the reference (sampling beyond the map)
    valid = (ys >= -1) & (ys <= h) & (xs >= -1) & (xs <= w)
    return out * valid


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """ref: paddle.vision.ops.roi_align.

    x: [B, C, H, W]; boxes: [R, 4] xyxy (concatenated over the batch,
    boxes_num[i] rois for image i); output [R, C, out_h, out_w].
    """
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    sr = sampling_ratio if sampling_ratio > 0 else 2
    bn = np.asarray(_arr(_t(boxes_num)))
    img_of_roi = jnp.asarray(np.repeat(np.arange(len(bn)), bn), jnp.int32)

    def f(xv, bx):
        off = 0.5 if aligned else 0.0
        bx = bx * spatial_scale - off
        rw = jnp.maximum(bx[:, 2] - bx[:, 0], 1e-3 if aligned else 1.0)
        rh = jnp.maximum(bx[:, 3] - bx[:, 1], 1e-3 if aligned else 1.0)
        # static sampling grid: [oh*sr] x [ow*sr] points per roi
        gy = (jnp.arange(oh * sr, dtype=jnp.float32) + 0.5) / (oh * sr)
        gx = (jnp.arange(ow * sr, dtype=jnp.float32) + 0.5) / (ow * sr)
        ys = bx[:, 1, None] + gy[None, :] * rh[:, None]   # [R, oh*sr]
        xs = bx[:, 0, None] + gx[None, :] * rw[:, None]   # [R, ow*sr]

        def per_roi(img_i, y, xcoord):
            feat = xv[img_i]                               # [C, H, W]
            yy = jnp.broadcast_to(y[:, None], (oh * sr, ow * sr))
            xx = jnp.broadcast_to(xcoord[None, :], (oh * sr, ow * sr))
            s = _bilinear_gather(feat, yy, xx)             # [C, ohsr, owsr]
            c = s.shape[0]
            s = s.reshape(c, oh, sr, ow, sr)
            return s.mean((2, 4))                          # [C, oh, ow]

        return jax.vmap(per_roi)(img_of_roi, ys, xs)

    return apply_op(f, _t(x), _t(boxes))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """ref: paddle.vision.ops.roi_pool — max over the integer pixels of
    each bin, evaluated on a static sr x sr sample grid snapped to pixel
    coords (exact when bins have <= sr pixels per side, subsampled max
    beyond that — documented static-shape approximation)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    sr = 8
    bn = np.asarray(_arr(_t(boxes_num)))
    img_of_roi = jnp.asarray(np.repeat(np.arange(len(bn)), bn), jnp.int32)

    def f(xv, bx):
        bx = bx * spatial_scale
        rw = jnp.maximum(bx[:, 2] - bx[:, 0], 1.0)
        rh = jnp.maximum(bx[:, 3] - bx[:, 1], 1.0)
        gy = (jnp.arange(oh * sr, dtype=jnp.float32) + 0.5) / (oh * sr)
        gx = (jnp.arange(ow * sr, dtype=jnp.float32) + 0.5) / (ow * sr)
        # snap samples to pixel indices (floor): max of true pixel values
        ys = jnp.floor(bx[:, 1, None] + gy[None, :] * rh[:, None])
        xs = jnp.floor(bx[:, 0, None] + gx[None, :] * rw[:, None])

        def per_roi(img_i, y, xcoord):
            feat = xv[img_i]
            h, w = feat.shape[-2:]
            yi = jnp.clip(y, 0, h - 1).astype(jnp.int32)
            xi = jnp.clip(xcoord, 0, w - 1).astype(jnp.int32)
            s = feat[:, yi[:, None], xi[None, :]]  # [C, oh*sr, ow*sr]
            c = s.shape[0]
            return s.reshape(c, oh, sr, ow, sr).max((2, 4))

        return jax.vmap(per_roi)(img_of_roi, ys, xs)

    return apply_op(f, _t(x), _t(boxes))


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """ref: paddle.vision.ops.distribute_fpn_proposals.

    TPU divergence (documented): XLA has no ragged outputs, so instead of
    per-level gathered roi lists this returns (level_idx [R], masks
    [L, R]) — callers select with the mask (multiply or where), keeping
    every shape static.
    """
    def f(rois):
        off = 1.0 if pixel_offset else 0.0
        w = rois[:, 2] - rois[:, 0] + off
        h = rois[:, 3] - rois[:, 1] + off
        scale = jnp.sqrt(jnp.maximum(w * h, 1e-9))
        lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-9)) + refer_level
        lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
        n_levels = max_level - min_level + 1
        masks = jax.nn.one_hot(lvl - min_level, n_levels,
                               dtype=jnp.float32).T  # [L, R]
        return lvl, masks
    return apply_op(f, _t(fpn_rois))


# ---------------------------------------------------------------------------
# deformable conv
# ---------------------------------------------------------------------------
def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """ref: paddle.vision.ops.deform_conv2d (v1; v2 when mask given).

    sample-then-matmul: bilinear-gather the kh*kw deformed taps for every
    output position, then a single einsum against the kernel — the gather
    is data-parallel over B*H*W (vmap), the contraction hits the MXU.

    x [B, Cin, H, W]; offset [B, 2*dg*kh*kw, Ho, Wo];
    weight [Cout, Cin/groups, kh, kw]; mask [B, dg*kh*kw, Ho, Wo].
    """
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)

    wshape = tuple(_arr(_t(weight)).shape)
    cout, cin_g, kh, kw = wshape

    def f(xv, off, wv, *rest):
        mask_v = None
        bias_v = None
        rest = list(rest)
        if mask is not None:
            mask_v = rest.pop(0)
        if bias is not None:
            bias_v = rest.pop(0)
        b, cin, h, w = xv.shape
        ho = (h + 2 * pd[0] - dl[0] * (kh - 1) - 1) // st[0] + 1
        wo = (w + 2 * pd[1] - dl[1] * (kw - 1) - 1) // st[1] + 1
        dg = deformable_groups
        off = off.reshape(b, dg, kh * kw, 2, ho, wo)
        # base sampling positions
        oy = jnp.arange(ho, dtype=jnp.float32) * st[0] - pd[0]   # [Ho]
        ox = jnp.arange(wo, dtype=jnp.float32) * st[1] - pd[1]   # [Wo]
        # tap grid flattened row-major to K = kh*kw (matches the offset
        # channel layout (dg, kh*kw, 2))
        ky = jnp.repeat(jnp.arange(kh, dtype=jnp.float32) * dl[0], kw)
        kx = jnp.tile(jnp.arange(kw, dtype=jnp.float32) * dl[1], kh)
        base_y = oy[None, :, None] + ky[:, None, None]  # [K, Ho, 1]
        base_x = ox[None, None, :] + kx[:, None, None]  # [K, 1, Wo]
        ys = base_y[None, None] + off[:, :, :, 0]   # [B, dg, K, Ho, Wo]
        xs = base_x[None, None] + off[:, :, :, 1]

        cpg = cin // dg  # channels per deformable group

        def per_image(feat, y, xcoord):
            # feat [Cin, H, W]; y/x [dg, K, Ho, Wo]
            def per_dg(fg, yy, xx):
                # fg [cpg, H, W]; yy/xx [K, Ho, Wo]
                return _bilinear_gather(fg, yy, xx)  # [cpg, K, Ho, Wo]
            return jax.vmap(per_dg)(
                feat.reshape(dg, cpg, h, w), y, xcoord)  # [dg,cpg,K,Ho,Wo]

        cols = jax.vmap(per_image)(xv, ys, xs)  # [B,dg,cpg,K,Ho,Wo]
        if mask_v is not None:
            cols = cols * mask_v.reshape(b, dg, 1, kh * kw, ho, wo)
        cols = cols.reshape(b, cin, kh * kw, ho, wo)
        # cols [B, Cin, K, Ho, Wo] x weight [Cout, Cin/g, kh*kw]
        wv2 = wv.reshape(cout, cin_g, kh * kw)
        if groups == 1:
            out = jnp.einsum("bckhw,ock->bohw", cols, wv2)
        else:
            cols_g = cols.reshape(b, groups, cin // groups, kh * kw, ho, wo)
            wv_g = wv2.reshape(groups, cout // groups, cin_g, kh * kw)
            out = jnp.einsum("bgckhw,gock->bgohw", cols_g, wv_g)
            out = out.reshape(b, cout, ho, wo)
        if bias_v is not None:
            out = out + bias_v.reshape(1, -1, 1, 1)
        return out

    args = [_t(x), _t(offset), _t(weight)]
    if mask is not None:
        args.append(_t(mask))
    if bias is not None:
        args.append(_t(bias))
    return apply_op(f, *args)


class DeformConv2D(Layer):
    """ref: paddle.vision.ops.DeformConv2D."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = ((kernel_size, kernel_size) if isinstance(kernel_size, int)
              else tuple(kernel_size))
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups) + ks, attr=weight_attr)
        self.bias = (None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True))

    def forward(self, x, offset, mask=None):
        return deform_conv2d(
            x, offset, self.weight, self.bias, stride=self._stride,
            padding=self._padding, dilation=self._dilation,
            deformable_groups=self._deformable_groups, groups=self._groups,
            mask=mask)


class RoIAlign(Layer):
    """ref: paddle.vision.ops.RoIAlign."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale)


class RoIPool(Layer):
    """ref: paddle.vision.ops.RoIPool."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


class PSRoIPool(Layer):
    """ref: paddle.vision.ops.PSRoIPool — position-sensitive RoI average
    pooling: channel c of output bin (i, j) reads ONLY input channel group
    c, position (i, j) (channel index c*oh*ow + i*ow + j). The sampling is
    done per-bin against its matched channel slice — 1/(oh*ow) the gather
    work of pooling all channels then selecting the diagonal."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = (output_size if isinstance(output_size, tuple)
                             else (output_size, output_size))
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        oh, ow = self._output_size
        scale = self._spatial_scale
        sr = 2
        bn = np.asarray(_arr(_t(boxes_num)))
        img_of_roi = jnp.asarray(np.repeat(np.arange(len(bn)), bn),
                                 jnp.int32)

        def f(xv, bx):
            b, c_total, h, w = xv.shape
            c_out = c_total // (oh * ow)
            bx = bx * scale
            rw = jnp.maximum(bx[:, 2] - bx[:, 0], 0.1)
            rh = jnp.maximum(bx[:, 3] - bx[:, 1], 0.1)
            gy = (jnp.arange(oh * sr, dtype=jnp.float32) + 0.5) / (oh * sr)
            gx = (jnp.arange(ow * sr, dtype=jnp.float32) + 0.5) / (ow * sr)
            ys = bx[:, 1, None] + gy[None, :] * rh[:, None]  # [R, oh*sr]
            xs = bx[:, 0, None] + gx[None, :] * rw[:, None]  # [R, ow*sr]

            def per_roi(img_i, y, xcoord):
                # [oh, ow, c_out, H, W]: bin (i, j) maps to its channel slice
                feat = xv[img_i].reshape(c_out, oh, ow, h, w)
                feat = jnp.moveaxis(feat, 0, 2)
                ybin = y.reshape(oh, sr)
                xbin = xcoord.reshape(ow, sr)

                def per_row(feat_row, yb):
                    def per_bin(feat_ij, xb):
                        yy = jnp.broadcast_to(yb[:, None], (sr, sr))
                        xx = jnp.broadcast_to(xb[None, :], (sr, sr))
                        return _bilinear_gather(feat_ij, yy, xx).mean((1, 2))
                    return jax.vmap(per_bin)(feat_row, xbin)  # [ow, c_out]
                out = jax.vmap(per_row)(feat, ybin)           # [oh, ow, c_out]
                return jnp.moveaxis(out, 2, 0)                # [c_out, oh, ow]

            return jax.vmap(per_roi)(img_of_roi, ys, xs)

        return apply_op(f, _t(x), _t(boxes))
