"""Vision transforms (ref: python/paddle/vision/transforms/transforms.py).

Numpy-based (host-side, feeds the DataLoader); HWC uint8 in, CHW float out
via ToTensor, matching the reference's conventions.
"""
from __future__ import annotations

import numbers
import random

import numpy as np

from ..tensor import Tensor

__all__ = ["Compose", "ToTensor", "Resize", "RandomHorizontalFlip",
           "RandomVerticalFlip", "Normalize", "Transpose", "CenterCrop",
           "RandomCrop", "RandomResizedCrop", "Pad", "BrightnessTransform",
           "ContrastTransform", "to_tensor", "normalize", "resize",
           "hflip", "vflip", "center_crop", "crop", "pad"]


def _size2(size):
    if isinstance(size, numbers.Number):
        return int(size), int(size)
    return int(size[0]), int(size[1])


def resize(img, size, interpolation="bilinear"):
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = _size2(size)
    ys = (np.arange(oh) + 0.5) * h / oh - 0.5
    xs = (np.arange(ow) + 0.5) * w / ow - 0.5
    if interpolation == "nearest":
        yi = np.clip(np.round(ys).astype(int), 0, h - 1)
        xi = np.clip(np.round(xs).astype(int), 0, w - 1)
        return img[yi][:, xi]
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None]
    wx = np.clip(xs - x0, 0, 1)[None, :]
    im = img.astype(np.float32)
    if im.ndim == 2:
        im = im[..., None]
        squeeze = True
    else:
        squeeze = False
    top = im[y0][:, x0] * (1 - wx[..., None]) + im[y0][:, x1] * wx[..., None]
    bot = im[y1][:, x0] * (1 - wx[..., None]) + im[y1][:, x1] * wx[..., None]
    out = top * (1 - wy[..., None]) + bot * wy[..., None]
    if squeeze:
        out = out[..., 0]
    if img.dtype == np.uint8:
        out = np.clip(out, 0, 255).astype(np.uint8)
    return out


def hflip(img):
    return img[:, ::-1].copy()


def vflip(img):
    return img[::-1].copy()


def crop(img, top, left, height, width):
    return img[top:top + height, left:left + width].copy()


def center_crop(img, output_size):
    th, tw = _size2(output_size)
    h, w = img.shape[:2]
    i = max((h - th) // 2, 0)
    j = max((w - tw) // 2, 0)
    return crop(img, i, j, th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    if isinstance(padding, int):
        padding = (padding,) * 4
    l, t, r, b = padding if len(padding) == 4 else \
        (padding[0], padding[1], padding[0], padding[1])
    width = [(t, b), (l, r)] + [(0, 0)] * (img.ndim - 2)
    if padding_mode == "constant":
        return np.pad(img, width, mode="constant", constant_values=fill)
    mode = {"reflect": "reflect", "edge": "edge", "symmetric": "symmetric"}[padding_mode]
    return np.pad(img, width, mode=mode)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        return (img - mean[:, None, None]) / std[:, None, None]
    return (img - mean) / std


def to_tensor(pic, data_format="CHW"):
    arr = np.asarray(pic)
    if arr.ndim == 2:
        arr = arr[..., None]
    arr = arr.astype(np.float32)
    if np.asarray(pic).dtype == np.uint8:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return arr


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return hflip(img)
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return vflip(img)
        return img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = _size2(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = pad(img, self.padding, self.fill, self.padding_mode)
        th, tw = self.size
        h, w = img.shape[:2]
        if self.pad_if_needed and (h < th or w < tw):
            img = pad(img, (0, max(th - h, 0), 0, max(tw - w, 0)), self.fill,
                      self.padding_mode)
            h, w = img.shape[:2]
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return crop(img, i, j, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = _size2(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]),
                                       np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = random.randint(0, h - ch)
                j = random.randint(0, w - cw)
                return resize(crop(img, i, j, ch, cw), self.size,
                              self.interpolation)
        return resize(center_crop(img, min(h, w)), self.size,
                      self.interpolation)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        out = img.astype(np.float32) * f
        return np.clip(out, 0, 255).astype(img.dtype) if img.dtype == np.uint8 else out


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        mean = img.mean()
        out = (img.astype(np.float32) - mean) * f + mean
        return np.clip(out, 0, 255).astype(img.dtype) if img.dtype == np.uint8 else out
