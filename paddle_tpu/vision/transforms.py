"""Vision transforms (ref: python/paddle/vision/transforms/transforms.py).

Numpy-based (host-side, feeds the DataLoader); HWC uint8 in, CHW float out
via ToTensor, matching the reference's conventions.
"""
from __future__ import annotations

import math
import numbers
import random

import numpy as np

from ..tensor import Tensor

__all__ = ["Compose", "ToTensor", "Resize", "RandomHorizontalFlip",
           "RandomVerticalFlip", "Normalize", "Transpose", "CenterCrop",
           "RandomCrop", "RandomResizedCrop", "Pad", "BrightnessTransform",
           "ContrastTransform", "SaturationTransform", "HueTransform",
           "ColorJitter", "to_tensor", "normalize", "resize",
           "hflip", "vflip", "center_crop", "crop", "pad",
           "erase", "affine", "perspective"]


def _size2(size):
    if isinstance(size, numbers.Number):
        return int(size), int(size)
    return int(size[0]), int(size[1])


def resize(img, size, interpolation="bilinear"):
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = _size2(size)
    ys = (np.arange(oh) + 0.5) * h / oh - 0.5
    xs = (np.arange(ow) + 0.5) * w / ow - 0.5
    if interpolation == "nearest":
        yi = np.clip(np.round(ys).astype(int), 0, h - 1)
        xi = np.clip(np.round(xs).astype(int), 0, w - 1)
        return img[yi][:, xi]
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None]
    wx = np.clip(xs - x0, 0, 1)[None, :]
    im = img.astype(np.float32)
    if im.ndim == 2:
        im = im[..., None]
        squeeze = True
    else:
        squeeze = False
    top = im[y0][:, x0] * (1 - wx[..., None]) + im[y0][:, x1] * wx[..., None]
    bot = im[y1][:, x0] * (1 - wx[..., None]) + im[y1][:, x1] * wx[..., None]
    out = top * (1 - wy[..., None]) + bot * wy[..., None]
    if squeeze:
        out = out[..., 0]
    if img.dtype == np.uint8:
        out = np.clip(out, 0, 255).astype(np.uint8)
    return out


def hflip(img):
    return img[:, ::-1].copy()


def vflip(img):
    return img[::-1].copy()


def crop(img, top, left, height, width):
    return img[top:top + height, left:left + width].copy()


def center_crop(img, output_size):
    th, tw = _size2(output_size)
    h, w = img.shape[:2]
    i = max((h - th) // 2, 0)
    j = max((w - tw) // 2, 0)
    return crop(img, i, j, th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    if isinstance(padding, int):
        padding = (padding,) * 4
    l, t, r, b = padding if len(padding) == 4 else \
        (padding[0], padding[1], padding[0], padding[1])
    width = [(t, b), (l, r)] + [(0, 0)] * (img.ndim - 2)
    if padding_mode == "constant":
        return np.pad(img, width, mode="constant", constant_values=fill)
    mode = {"reflect": "reflect", "edge": "edge", "symmetric": "symmetric"}[padding_mode]
    return np.pad(img, width, mode=mode)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        return (img - mean[:, None, None]) / std[:, None, None]
    return (img - mean) / std


def to_tensor(pic, data_format="CHW"):
    arr = np.asarray(pic)
    if arr.ndim == 2:
        arr = arr[..., None]
    arr = arr.astype(np.float32)
    if np.asarray(pic).dtype == np.uint8:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return arr


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return hflip(img)
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return vflip(img)
        return img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = _size2(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = pad(img, self.padding, self.fill, self.padding_mode)
        th, tw = self.size
        h, w = img.shape[:2]
        if self.pad_if_needed and (h < th or w < tw):
            img = pad(img, (0, max(th - h, 0), 0, max(tw - w, 0)), self.fill,
                      self.padding_mode)
            h, w = img.shape[:2]
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return crop(img, i, j, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = _size2(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]),
                                       np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = random.randint(0, h - ch)
                j = random.randint(0, w - cw)
                return resize(crop(img, i, j, ch, cw), self.size,
                              self.interpolation)
        return resize(center_crop(img, min(h, w)), self.size,
                      self.interpolation)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


# ---------------------------------------------------------------------------
# round-2 long-tail transforms (ref: python/paddle/vision/transforms/
# transforms.py + functional.py). Host-side numpy like the rest of this
# module — transforms run in the input pipeline, not on the TPU.
# ---------------------------------------------------------------------------
def adjust_brightness(img, brightness_factor):
    """ref: F.adjust_brightness."""
    out = np.asarray(img).astype(np.float32) * float(brightness_factor)
    a = np.asarray(img)
    return np.clip(out, 0, 255).astype(a.dtype) if a.dtype == np.uint8 \
        else out


def adjust_contrast(img, contrast_factor):
    """ref: F.adjust_contrast."""
    a = np.asarray(img)
    mean = a.astype(np.float32).mean()
    out = (a.astype(np.float32) - mean) * float(contrast_factor) + mean
    return np.clip(out, 0, 255).astype(a.dtype) if a.dtype == np.uint8 \
        else out


def adjust_hue(img, hue_factor):
    """ref: F.adjust_hue — hue rotation via HSV round trip."""
    assert -0.5 <= hue_factor <= 0.5
    a = np.asarray(img).astype(np.float32)
    scale = 255.0 if np.asarray(img).dtype == np.uint8 else 1.0
    rgb = a / scale if scale != 1.0 else a
    # rgb<->hsv (vectorized, channels-last)
    maxc = rgb.max(-1)
    minc = rgb.min(-1)
    v = maxc
    d = maxc - minc
    s = np.where(maxc > 0, d / np.maximum(maxc, 1e-12), 0.0)
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    dd = np.maximum(d, 1e-12)
    h = np.where(maxc == r, ((g - b) / dd) % 6,
                 np.where(maxc == g, (b - r) / dd + 2, (r - g) / dd + 4))
    h = np.where(d == 0, 0.0, h) / 6.0
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6).astype(int)
    f = h * 6 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = (i % 6)[..., None]  # broadcast against the stacked channel dim
    out = np.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    out = out * scale if scale != 1.0 else out
    adt = np.asarray(img).dtype
    return np.clip(out, 0, 255).astype(adt) if adt == np.uint8 else out


def to_grayscale(img, num_output_channels=1):
    """ref: F.to_grayscale (ITU-R 601-2 luma)."""
    a = np.asarray(img).astype(np.float32)
    gray = a[..., 0] * 0.299 + a[..., 1] * 0.587 + a[..., 2] * 0.114
    out = np.repeat(gray[..., None], num_output_channels, -1)
    adt = np.asarray(img).dtype
    return np.clip(out, 0, 255).astype(adt) if adt == np.uint8 else out


def erase(img, i, j, h, w, v, inplace=False):
    """ref: paddle.vision.transforms.erase — set the [i:i+h, j:j+w]
    rectangle to value `v` (scalar or broadcastable array)."""
    a = np.asarray(img)
    if not inplace:
        a = a.copy()
    vv = np.asarray(v)
    a[i:i + h, j:j + w] = vv.astype(a.dtype) if vv.dtype != a.dtype \
        else vv
    return a


def affine(img, angle, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="nearest", fill=0, center=None):
    """ref: paddle.vision.transforms.affine — deterministic affine
    resample: rotation (degrees) + translation (px) + scale + shear
    (degrees, x then optional y), about `center` (default image
    center). The inverse-map core shared with RandomAffine."""
    a = np.asarray(img)
    h, w = a.shape[:2]
    if isinstance(shear, (int, float)):
        shear = (shear, 0.0)
    shx, shy = (tuple(shear) + (0.0,))[:2]
    tx, ty = translate
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None \
        else (center[1], center[0])
    ang, shx, shy = (math.radians(angle), math.radians(shx),
                     math.radians(shy))
    cos, sin = math.cos(ang), math.sin(ang)
    S = np.array([[1.0, math.tan(shx)], [math.tan(shy), 1.0]])
    R = np.array([[cos, -sin], [sin, cos]])
    M = (R @ S) * scale
    Minv = np.linalg.inv(M)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    dx = xx - cx - tx
    dy = yy - cy - ty
    xs = Minv[0, 0] * dx + Minv[0, 1] * dy + cx
    ys = Minv[1, 0] * dx + Minv[1, 1] * dy + cy
    return _inverse_map_sample(a, xs, ys, interpolation, fill)


def _homography(src_pts, dst_pts):
    A = []
    for (x, y), (u, v) in zip(src_pts, dst_pts):
        A.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
        A.append([0, 0, 0, x, y, 1, -v * x, -v * y])
    A = np.asarray(A, np.float64)
    b = np.asarray(dst_pts, np.float64).reshape(-1)
    h8 = np.linalg.solve(A, b)
    return np.append(h8, 1.0).reshape(3, 3)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """ref: paddle.vision.transforms.perspective — projective warp
    taking the 4 startpoints to the 4 endpoints (inverse-map
    resample)."""
    a = np.asarray(img)
    h, w = a.shape[:2]
    M = _homography(endpoints, startpoints)   # output pixel -> source
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    ones = np.ones_like(xx)
    pts = np.stack([xx, yy, ones], 0).reshape(3, -1)
    mapped = M @ pts
    xs = (mapped[0] / mapped[2]).reshape(h, w)
    ys = (mapped[1] / mapped[2]).reshape(h, w)
    return _inverse_map_sample(a, xs, ys, interpolation, fill)


def _inverse_map_sample(a, xs, ys, interpolation="nearest", fill=0):
    """Sample source image `a` at float positions (ys, xs) (one per output
    pixel); out-of-bounds positions take `fill`. Shared by rotate /
    RandomAffine / RandomPerspective."""
    h, w = a.shape[:2]

    def gather(yi, xi):
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        yic = np.clip(yi, 0, h - 1)
        xic = np.clip(xi, 0, w - 1)
        px = a[yic, xic].astype(np.float32)
        mask = valid[..., None] if a.ndim == 3 else valid
        return np.where(mask, px, float(fill))

    if interpolation == "bilinear":
        x0 = np.floor(xs).astype(int)
        y0 = np.floor(ys).astype(int)
        wx = (xs - x0)
        wy = (ys - y0)
        if a.ndim == 3:
            wx = wx[..., None]
            wy = wy[..., None]
        out = (gather(y0, x0) * (1 - wy) * (1 - wx)
               + gather(y0, x0 + 1) * (1 - wy) * wx
               + gather(y0 + 1, x0) * wy * (1 - wx)
               + gather(y0 + 1, x0 + 1) * wy * wx)
    else:
        out = gather(np.round(ys).astype(int), np.round(xs).astype(int))
    return np.clip(out, 0, 255).astype(a.dtype) if a.dtype == np.uint8 \
        else out.astype(a.dtype)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """ref: F.rotate — inverse-map nearest/bilinear resample (numpy).
    expand=True enlarges the canvas to contain the whole rotated image."""
    a = np.asarray(img)
    h, w = a.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None \
        else (center[1], center[0])
    th = np.deg2rad(angle)
    cos, sin = np.cos(th), np.sin(th)
    if expand:
        oh = int(math.ceil(abs(h * cos) + abs(w * sin)))
        ow = int(math.ceil(abs(w * cos) + abs(h * sin)))
        ocy, ocx = (oh - 1) / 2.0, (ow - 1) / 2.0
    else:
        oh, ow = h, w
        ocy, ocx = cy, cx
    yy, xx = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
    xs = cos * (xx - ocx) + sin * (yy - ocy) + cx
    ys = -sin * (xx - ocx) + cos * (yy - ocy) + cy
    return _inverse_map_sample(a, xs, ys, interpolation, fill)


class SaturationTransform(BaseTransform):
    """ref: transforms.SaturationTransform."""

    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        gray = to_grayscale(img, 3).astype(np.float32)
        out = img.astype(np.float32) * f + gray * (1 - f)
        return np.clip(out, 0, 255).astype(img.dtype) \
            if img.dtype == np.uint8 else out


class HueTransform(BaseTransform):
    """ref: transforms.HueTransform."""

    def __init__(self, value, keys=None):
        assert 0 <= value <= 0.5
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """ref: transforms.ColorJitter — randomly jitter brightness, contrast,
    saturation and hue, applying the four constituent transforms in a
    random order per call (matches the reference's _get_param shuffle)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.brightness = float(brightness)
        self.contrast = float(contrast)
        self.saturation = float(saturation)
        self.hue = float(hue)
        self._parts = [BrightnessTransform(self.brightness),
                       ContrastTransform(self.contrast),
                       SaturationTransform(self.saturation),
                       HueTransform(self.hue)]

    def _apply_image(self, img):
        order = list(range(4))
        random.shuffle(order)
        for i in order:
            img = self._parts[i]._apply_image(np.asarray(img))
        return img


class Grayscale(BaseTransform):
    """ref: transforms.Grayscale."""

    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class RandomRotation(BaseTransform):
    """ref: transforms.RandomRotation."""

    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, (int, float)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, self.expand,
                      self.center, self.fill)


class RandomErasing(BaseTransform):
    """ref: transforms.RandomErasing — erase a random rectangle.
    value='random' fills with gaussian noise like the reference; the
    `inplace` flag is accepted (this numpy pipeline always copies)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        if random.random() > self.prob:
            return img
        a = np.array(img, copy=True)
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = math.exp(random.uniform(math.log(self.ratio[0]),
                                         math.log(self.ratio[1])))
            eh = int(round(math.sqrt(target * ar)))
            ew = int(round(math.sqrt(target / ar)))
            if eh < h and ew < w:
                top = random.randint(0, h - eh)
                left = random.randint(0, w - ew)
                patch_shape = (eh, ew) + a.shape[2:]
                if isinstance(self.value, str):  # 'random'
                    noise = np.random.standard_normal(patch_shape)
                    if a.dtype == np.uint8:
                        noise = np.clip(noise * 255, 0, 255)
                    return erase(a, top, left, eh, ew,
                                 noise.astype(a.dtype), inplace=True)
                return erase(a, top, left, eh, ew, self.value,
                             inplace=True)
        return a


class RandomAffine(BaseTransform):
    """ref: transforms.RandomAffine — one inverse-map affine resample
    covering rotation + translation + scale + shear (2- or 4-element
    shear ranges like the reference)."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        if isinstance(degrees, (int, float)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.translate = translate
        self.scale_range = scale
        if shear is not None and isinstance(shear, (int, float)):
            shear = (-abs(shear), abs(shear))
        self.shear = None if shear is None else list(shear)
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        a = np.asarray(img)
        h, w = a.shape[:2]
        angle = random.uniform(*self.degrees)
        s = (random.uniform(*self.scale_range)
             if self.scale_range is not None else 1.0)
        shx = shy = 0.0
        if self.shear is not None:
            shx = random.uniform(self.shear[0], self.shear[1])
            if len(self.shear) == 4:
                shy = random.uniform(self.shear[2], self.shear[3])
        tx = (random.uniform(-self.translate[0], self.translate[0]) * w
              if self.translate is not None else 0.0)
        ty = (random.uniform(-self.translate[1], self.translate[1]) * h
              if self.translate is not None else 0.0)
        return affine(a, angle, (tx, ty), s, (shx, shy),
                      interpolation=self.interpolation, fill=self.fill,
                      center=self.center)


class RandomPerspective(BaseTransform):
    """ref: transforms.RandomPerspective — random 4-point projective warp
    (inverse-map nearest resample)."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        if random.random() > self.prob:
            return img
        a = np.asarray(img)
        h, w = a.shape[:2]
        d = self.distortion_scale
        dx = lambda: random.uniform(0, d * w / 2)  # noqa: E731
        dy = lambda: random.uniform(0, d * h / 2)  # noqa: E731
        endpoints = [(dx(), dy()), (w - 1 - dx(), dy()),
                     (w - 1 - dx(), h - 1 - dy()), (dx(), h - 1 - dy())]
        startpoints = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        return perspective(a, startpoints, endpoints,
                           self.interpolation, self.fill)


class ToPILImage(BaseTransform):
    """ref: transforms.ToPILImage."""

    def __init__(self, mode=None, keys=None):
        self.mode = mode

    def _apply_image(self, img):
        from PIL import Image
        a = np.asarray(img)
        if a.dtype != np.uint8:
            a = np.clip(a * 255 if a.max() <= 1.0 else a, 0,
                        255).astype(np.uint8)
        if a.ndim == 3 and a.shape[0] in (1, 3) and a.shape[-1] not in (1, 3):
            a = np.transpose(a, (1, 2, 0))  # CHW -> HWC
        if a.ndim == 3 and a.shape[-1] == 1:
            a = a[..., 0]
        return Image.fromarray(a, mode=self.mode)


AdjustBrightness = BrightnessTransform
AdjustContrast = ContrastTransform
