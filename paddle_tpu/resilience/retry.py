"""Retry/backoff wrapper for transient runtime errors.

TPU runtimes surface recoverable conditions as textual status codes
(RESOURCE_EXHAUSTED while another client's pages drain, UNAVAILABLE /
DEADLINE_EXCEEDED across a flaky tunnel, ABORTED on a preempted
dispatch). Those deserve a bounded, deterministic backoff-and-retry at
the dispatch seam — not a dead training job. Everything else (shape
errors, OOM of the program itself, assertion failures) must propagate
untouched.

Deterministic by design: delays are a fixed exponential ladder (no
jitter by default) so chaos tests assert exact retry counts and the
campaign replays identically under a fixed seed. Jitter is OPT-IN and
itself seeded (``jitter=``/``jitter_seed=``): N fleet replicas
retrying the same transient fault would otherwise back off in
lockstep and re-collide as a thundering herd — each replica passes its
own seed, so the schedules de-synchronize but any single schedule
still replays bit-identically.
"""
from __future__ import annotations

import random
import time

from .faults import TransientError

__all__ = ["TransientError", "is_transient", "retryable_for",
           "call_with_retries", "backoff_schedule", "RetryStats"]

# status-code grammar shared by PJRT/XLA runtime errors; matched against
# str(exc) because the concrete exception types vary by jaxlib version
_TRANSIENT_MARKERS = ("RESOURCE_EXHAUSTED", "UNAVAILABLE",
                      "DEADLINE_EXCEEDED", "ABORTED",
                      "connection reset", "Socket closed")


def is_transient(exc):
    """Retryable? Injected TransientErrors always are; real errors only
    when their message carries a transient status code."""
    if isinstance(exc, TransientError):
        return True
    if isinstance(exc, (RuntimeError, OSError, ConnectionError)):
        msg = str(exc)
        return any(m in msg for m in _TRANSIENT_MARKERS)
    return False


def retryable_for(donate):
    """The canonical dispatch-seam retry predicate. Under buffer
    donation a REAL mid-execute failure has already consumed the
    donated arrays, so only injected TransientErrors — which seams
    raise BEFORE the execute — are safely retryable; without donation
    the full transient grammar is."""
    if donate:
        return lambda e: isinstance(e, TransientError)
    return is_transient


class RetryStats:
    """Mutable counter bag a caller can thread through many
    call_with_retries sites (TrainGuard and ServingEngine each own
    one; health()/log_scalars() surface it)."""

    __slots__ = ("retries", "gave_up")

    def __init__(self):
        self.retries = 0
        self.gave_up = 0

    def as_dict(self):
        return {"retries": self.retries, "gave_up": self.gave_up}


def backoff_schedule(retries, base_delay=0.05, max_delay=2.0,
                     jitter=0.0, jitter_seed=0):
    """The exact delays call_with_retries would sleep, precomputed:
    delay[i] = min(base_delay * 2**i, max_delay), each stretched by a
    factor in [1, 1+jitter) drawn from ``random.Random(jitter_seed)``.
    jitter=0 (the default) is the historical exact ladder; with jitter
    on, the schedule is a pure function of the seed — two replicas
    with different seeds spread out, one replica replays identically."""
    rng = random.Random(jitter_seed)
    out = []
    for attempt in range(max(0, int(retries))):
        d = min(base_delay * (2 ** attempt), max_delay)
        if jitter:
            d *= 1.0 + float(jitter) * rng.random()
        out.append(d)
    return out


def call_with_retries(fn, *args, retries=3, base_delay=0.05,
                      max_delay=2.0, retryable=is_transient,
                      stats=None, on_retry=None, jitter=0.0,
                      jitter_seed=0, **kwargs):
    """Run fn(*args, **kwargs); on a retryable error, back off
    (base_delay * 2**attempt, capped; optionally seeded-jittered — see
    backoff_schedule) and retry up to `retries` times. The final
    failure re-raises the last error unchanged.

    CAUTION at donating seams: a retry re-submits the same argument
    arrays, which is only safe when the failure happened before the
    donated buffers were consumed. The engine/serving dispatch seams
    therefore pass a narrowed `retryable` when donation is on —
    injected TransientErrors (raised BEFORE the execute) retry, real
    runtime errors from the execute itself propagate."""
    delays = backoff_schedule(retries, base_delay, max_delay,
                              jitter=jitter, jitter_seed=jitter_seed)
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 — filtered by retryable()
            if not retryable(e) or attempt >= retries:
                if stats is not None and retryable(e):
                    stats.gave_up += 1
                raise
            if stats is not None:
                stats.retries += 1
            if on_retry is not None:
                on_retry(e, attempt)
            time.sleep(delays[attempt])
            attempt += 1
