"""TrainGuard — NaN/inf skip, snapshot ring, rollback.

A NaN storm (bad batch, overflowed bf16 reduction, cosmic-ray HBM
flip) must cost skipped steps, not a dead run or a silently-poisoned
model. The guard splits the work across the compile boundary:

in-step (compiled, zero extra dispatch — see Engine._build_train_fn's
guarded variant):
  - an all-finite check over loss AND every gradient leaf, fused into
    the same XLA program as the step (the reductions fuse into the
    grad computation's epilogue; nothing extra launches);
  - the param/buffer/optimizer update is masked by that flag, so a bad
    step is a perfect no-op on model state;
  - when a GradScaler is attached, its dynamic-scale state lives
    in-step too (loss scaled pre-grad, grads unscaled pre-check,
    functional_update on the found-inf flag).

host-side (this object):
  - skip counters + consecutive-bad tracking;
  - a last-good snapshot ring (params + buffers + opt state + update
    counters, device_get to host numpy so donation can't invalidate
    it) refreshed every `snapshot_every` good steps;
  - rollback to the newest ring entry after `rollback_after`
    consecutive bad steps — the backstop for corruption the in-step
    mask can't catch (state that was already non-finite when the
    guard attached, or a poisoned running stat from an unguarded
    phase);
  - a bounded retry/backoff around the dispatch for transient
    RESOURCE_EXHAUSTED-style runtime errors (retry.py).

Attach via ``Model.prepare(..., guard=TrainGuard(...))`` or
``engine.attach_guard(TrainGuard(...))``. Applies to the fused
train_batch path; gradient accumulation keeps its own two-program
structure and refuses a guard loudly rather than half-protecting.
"""
from __future__ import annotations

import collections

import jax
import numpy as np

from .retry import RetryStats

__all__ = ["TrainGuard"]


def _to_host(tree):
    """Snapshot copy: device_get every array leaf to host numpy.
    Donation-proof (the engine's next step may delete the device
    buffers; numpy copies survive) and mesh-agnostic (device_get
    consolidates sharded arrays; restore re-places them lazily)."""
    def one(x):
        if isinstance(x, jax.Array):
            return np.asarray(jax.device_get(x))
        return x
    return jax.tree_util.tree_map(one, tree)


def _to_device(tree):
    import jax.numpy as jnp

    def one(x):
        if isinstance(x, np.ndarray):
            return jnp.asarray(x)
        return x
    return jax.tree_util.tree_map(one, tree)


class TrainGuard:
    """Host-side half of the guarded train step.

    snapshot_every: good steps between snapshot-ring refreshes. COST:
        each snapshot device_gets params + buffers + optimizer state
        to host numpy (a full HBM->host fetch, ~3x param bytes under
        Adam) and the ring holds ring_size such copies. The defaults
        suit small/medium models; for multi-GB models raise
        snapshot_every to a few hundred, set ring_size=1, or skip the
        ring entirely and lean on PreemptionCheckpoint(every_n_steps=)
        whose CheckpointManager write is async and disk-backed.
    ring_size: retained snapshots (newest wins on rollback; older
        entries are the defense against a corrupt newest).
    rollback_after: consecutive bad steps that trigger a rollback.
    scaler: optional amp.GradScaler — its dynamic loss scale compiles
        into the step and its found-inf/skip counters track the guard.
    retries / retry_base_delay: transient-dispatch retry budget.
    """

    def __init__(self, snapshot_every=10, ring_size=2, rollback_after=3,
                 scaler=None, retries=2, retry_base_delay=0.05):
        if snapshot_every < 1:
            raise ValueError("snapshot_every must be >= 1")
        if rollback_after < 1:
            raise ValueError("rollback_after must be >= 1")
        self.snapshot_every = int(snapshot_every)
        self.rollback_after = int(rollback_after)
        self.scaler = scaler
        self.retries = int(retries)
        self.retry_base_delay = float(retry_base_delay)
        self.ring = collections.deque(maxlen=int(ring_size))
        self.retry_stats = RetryStats()
        # counters (log_scalars surfaces these in fit() logs)
        self.good_steps = 0
        self.skipped_steps = 0
        self.consecutive_bad = 0
        self.rollbacks = 0
        self.last_outcome = "ok"   # ok | skipped | rolled_back
        self._since_snapshot = 0
        self._lr_refresh_pending = False

    # -- snapshots ---------------------------------------------------------
    @staticmethod
    def _lr_sched(engine):
        from ..optimizer.lr import LRScheduler
        opt = engine.optimizer
        lr = getattr(opt, "_lr", None)
        return lr if isinstance(lr, LRScheduler) else None

    def snapshot(self, engine):
        """Capture last-good training state (host copies) — including
        the LR scheduler position: a rollback that rewinds opt_step
        but left the schedule ahead would replay the window under the
        wrong learning rates."""
        import copy
        sched = self._lr_sched(engine)
        self.ring.append({
            "params": _to_host(engine._params),
            "buffers": _to_host(engine._buffers),
            "opt_state": _to_host(engine._opt_state),
            "scaler_state": _to_host(engine._scaler_state),
            "opt_step": engine._opt_step,
            "lr_sched": None if sched is None
            else copy.deepcopy(sched.state_dict()),
        })
        self._since_snapshot = 0
        # hapi steps the scheduler AFTER the engine call this snapshot
        # ran inside of; note_lr_stepped refreshes the captured
        # position so it matches the snapshot's opt_step
        self._lr_refresh_pending = True

    def note_lr_stepped(self, engine):
        """Call right after advancing the LR scheduler for an applied
        update (hapi does): re-captures the newest snapshot's
        scheduler position if that snapshot was taken this step."""
        if getattr(self, "_lr_refresh_pending", False) and self.ring:
            sched = self._lr_sched(engine)
            if sched is not None:
                import copy
                self.ring[-1]["lr_sched"] = copy.deepcopy(
                    sched.state_dict())
        self._lr_refresh_pending = False

    def rollback(self, engine):
        """Restore the newest snapshot into the engine. Returns True if
        a snapshot existed. On a single-device engine the compiled step
        is reused (structurally identical trees — no recompile); under
        GroupSharded/ZeRO the restored leaves must be RE-PLACED on
        their shardings and the programs rebuilt, mirroring
        Engine.load_opt_state_dict — a default-device restore would
        materialize the full tree on one chip mid-recovery."""
        if not self.ring:
            return False
        snap = self.ring[-1]
        engine._params = _to_device(snap["params"])
        engine._buffers = _to_device(snap["buffers"])
        engine._opt_state = _to_device(snap["opt_state"])
        engine._scaler_state = _to_device(snap["scaler_state"])
        engine._opt_step = snap["opt_step"]
        if getattr(engine.optimizer, "_group_sharded", None) is not None:
            engine._apply_zero_placement()
            engine._train_fn = None
            engine._multi_fns = {}
        sched = self._lr_sched(engine)
        if sched is not None and snap.get("lr_sched") is not None:
            import copy
            sched.set_state_dict(copy.deepcopy(snap["lr_sched"]))
        engine.network.load_raw_state(engine._params, engine._buffers)
        engine.reset_accum_window()
        self.rollbacks += 1
        self.consecutive_bad = 0
        return True

    # -- per-step bookkeeping ---------------------------------------------
    def before_first_step(self, engine):
        """Seed the ring so a storm in the first window can roll back
        to the initialization state."""
        if not self.ring:
            self.snapshot(engine)

    def after_step(self, engine, ok):
        """Called by the engine with the step's host-synced finite
        flag. Returns 'ok' | 'skipped' | 'rolled_back' (also kept on
        .last_outcome — hapi gates the LR-scheduler step on it, so the
        schedule position tracks APPLIED updates like opt_step does)."""
        if self.scaler is not None:
            self.scaler.note_step(found_inf=not ok)
        # only a snapshot taken THIS step may have its LR position
        # refreshed by a following note_lr_stepped
        self._lr_refresh_pending = False
        if ok:
            self.good_steps += 1
            self.consecutive_bad = 0
            self._since_snapshot += 1
            if self._since_snapshot >= self.snapshot_every:
                self.snapshot(engine)
            self.last_outcome = "ok"
            rolled = False
        else:
            self.skipped_steps += 1
            self.consecutive_bad += 1
            rolled = self.consecutive_bad >= self.rollback_after \
                and self.rollback(engine)
            self.last_outcome = "rolled_back" if rolled else "skipped"
        # every guarded step leaves a flight-recorder breadcrumb — the
        # rollback dump below must contain the storm's own step
        # records, so the note lands BEFORE the dump (and only a step
        # that ROLLED BACK dumps: a storm outlasting rollback_after
        # keeps skipping afterwards, it does not re-dump per step)
        self._flight_note(engine, ok)
        if rolled:
            self._flight_dump(engine)
        return self.last_outcome

    def _flight_note(self, engine, ok):
        try:
            from ..observability import flightrec
            flightrec.note("guard_step", step=engine._step, ok=bool(ok),
                           outcome=self.last_outcome,
                           consecutive_bad=self.consecutive_bad,
                           skipped_steps=self.skipped_steps)
        except Exception:  # noqa: BLE001 — accounting never kills a step
            pass

    def _flight_dump(self, engine):
        """Rollback is a flight-recorder trigger (docs/observability.md):
        the ring of recent step records + guard stats lands in
        flight_rollback.json so the postmortem sees WHICH steps fed
        the storm. Never raises — recovery must not die to disk."""
        try:
            from ..observability import flightrec
            flightrec.note("guard_rollback", step=engine._step,
                           **self.stats())
            flightrec.dump("rollback",
                           extra={"guard": self.stats(),
                                  "step": engine._step})
        except Exception:  # noqa: BLE001
            pass

    # -- reporting ---------------------------------------------------------
    def log_scalars(self):
        """Flat numeric dict for hapi fit() logs / health snapshots."""
        out = {"skipped": self.skipped_steps,
               "rollbacks": self.rollbacks}
        if self.retry_stats.retries:
            out["retries"] = self.retry_stats.retries
        if self.scaler is not None:
            out["found_inf"] = self.scaler.found_inf_count
        return out

    def stats(self):
        return {"good_steps": self.good_steps,
                "skipped_steps": self.skipped_steps,
                "consecutive_bad": self.consecutive_bad,
                "rollbacks": self.rollbacks,
                "snapshots": len(self.ring),
                **self.retry_stats.as_dict()}
