"""Resilience subsystem: fault injection, train guard, preemption,
retry, watchdog.

A production TPU stack's uptime is decided by its behavior under
faults — NaN storms, pod preemption, page exhaustion, transient
runtime errors, wedged dispatches. This package is that layer, built
around a deterministic fault-injection registry (faults.py) so every
behavior drills on CPU tier-1:

- faults:      env/context-driven injection registry + seam helpers
- TrainGuard:  in-step all-finite check, skip counters, snapshot ring,
               rollback (guard.py; compiled half in hapi/engine.py)
- preemption:  SIGTERM/SIGINT -> flag -> checkpoint-and-exit helpers
- retry:       bounded deterministic backoff for transient errors
- Watchdog:    wedged-dispatch detection (serving health())

See docs/robustness.md for the failure model and injection points.
"""
from . import faults  # noqa: F401
from . import preemption  # noqa: F401
from .faults import TransientError, inject, scenario  # noqa: F401
from .guard import TrainGuard  # noqa: F401
from .retry import (RetryStats, backoff_schedule,  # noqa: F401
                    call_with_retries, is_transient)
from .watchdog import Watchdog  # noqa: F401

__all__ = ["faults", "preemption", "TrainGuard", "Watchdog",
           "TransientError", "RetryStats", "inject", "scenario",
           "call_with_retries", "backoff_schedule", "is_transient"]

# arm any env-specified faults at first import of the subsystem — the
# chaos_smoke campaign stage and the SIGTERM drill ride this
faults.load_env()
