"""Preemption-safe shutdown: SIGTERM/SIGINT -> checkpoint-and-exit.

Pod preemption delivers SIGTERM with a grace window. The handler here
only sets a flag — all real work (finishing the in-flight step,
writing the checkpoint through CheckpointManager's crash-safe finalize
path) happens at the next host step boundary, where training state is
consistent. hapi's fit() polls `requested()` every batch; the
PreemptionCheckpoint callback (hapi/callbacks.py) turns the flag into
a finalized checkpoint + clean stop, and `restore_training_state`
resumes loss-exact.

The chaos suite injects the signal itself via the `sigterm` fault kind
(faults.maybe_sigterm at the same fit() boundary), so the whole path
drills deterministically in-process.
"""
from __future__ import annotations

import signal
import threading

__all__ = ["install", "installed", "requested", "request", "clear",
           "save_training_state", "restore_training_state"]

_flag = threading.Event()
_installed_for: dict[int, object] = {}   # signum -> previous handler


def install(signals=(signal.SIGTERM, signal.SIGINT), chain=True):
    """Install flag-setting handlers (idempotent). chain=True also
    invokes the previously-installed USER handler — a supervisor's own
    SIGTERM bookkeeping keeps working underneath ours. Python's
    default SIGINT handler is NOT chained: it raises
    KeyboardInterrupt mid-step, which is exactly the unclean unwind
    this module exists to replace with a boundary checkpoint."""
    for signum in signals:
        if signum in _installed_for:
            continue
        prev = signal.getsignal(signum)
        _installed_for[signum] = prev
        chain_prev = (chain and callable(prev)
                      and prev is not signal.default_int_handler)

        def _handler(num, frame, _prev=prev, _chain=chain_prev):
            _flag.set()
            if _chain:
                _prev(num, frame)

        signal.signal(signum, _handler)


def uninstall():
    """Restore the pre-install handlers (test hygiene)."""
    for signum, prev in list(_installed_for.items()):
        try:
            signal.signal(signum, prev)
        except (ValueError, TypeError):
            pass
        del _installed_for[signum]


def installed():
    return bool(_installed_for)


def requested():
    """True once a preemption signal arrived (sticky until clear())."""
    return _flag.is_set()


def request():
    """Programmatic preemption (tests, external orchestrators)."""
    _flag.set()


def clear():
    _flag.clear()


# -- full-training-state payloads (exact resume) --------------------------

def save_training_state(model, manager, metric=None):
    """Checkpoint EVERYTHING exact resume needs through a
    CheckpointManager: params, optimizer moments + update counters, LR
    scheduler position, scaler counters. Returns the step saved at.
    The manager's COMPLETE-marker finalize makes the write crash-safe;
    callers exiting on preemption should manager.wait() after."""
    eng = model._ensure_engine()
    eng.sync_to_layer()
    step = eng._step
    state = {"model": model.network.state_dict(),
             "opt": eng.opt_state_dict(),
             "scaler_state": eng._scaler_state}
    opt = model._optimizer
    if opt is not None:
        from ..optimizer.lr import LRScheduler
        if isinstance(opt._lr, LRScheduler):
            state["lr_sched"] = opt._lr.state_dict()
    guard = getattr(eng, "guard", None)
    if guard is not None and guard.scaler is not None:
        state["scaler"] = guard.scaler.state_dict()
    manager.save(step, state, metric=metric)
    return step


def restore_training_state(model, manager, step=None):
    """Inverse of save_training_state: load the latest finalized
    checkpoint (or `step`) into the model/engine. Returns the restored
    step, or None when the manager holds nothing usable.

    Also resets the preemption flag and the model's stop_training
    latch: restoring IS the start of the resumed incarnation, and a
    flag left over from the previous fit (in-process restarts,
    supervisors that re-enter) would otherwise kill the resumed fit
    after one batch."""
    state = manager.restore(step=step)
    if state is None:
        return None
    clear()
    model.stop_training = False
    model.network.set_state_dict(state["model"])
    eng = model._ensure_engine()
    eng.sync_from_layer()
    import jax
    import jax.numpy as jnp

    def dev(x):
        import numpy as np
        return jnp.asarray(x) if isinstance(x, np.ndarray) else x
    eng.load_opt_state_dict(jax.tree_util.tree_map(dev, state["opt"]))
    if state.get("scaler_state") is not None:
        eng._scaler_state = jax.tree_util.tree_map(
            dev, state["scaler_state"])
    opt = model._optimizer
    if opt is not None and "lr_sched" in state:
        from ..optimizer.lr import LRScheduler
        if isinstance(opt._lr, LRScheduler):
            opt._lr.set_state_dict(state["lr_sched"])
    guard = getattr(eng, "guard", None)
    if guard is not None and guard.scaler is not None \
            and "scaler" in state:
        guard.scaler.load_state_dict(state["scaler"])
    return state["opt"]["step"]
