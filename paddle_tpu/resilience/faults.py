"""Deterministic fault-injection registry — the chaos-campaign backbone.

A production TPU stack dies from unhandled faults (NaN storms, pod
preemption, page exhaustion, wedged dispatches), not slow kernels.
Every resilience behavior in this repo is therefore driven by a seam
that consults this registry, so the whole failure model is testable on
CPU tier-1 with zero nondeterminism:

    with faults.scenario(("nan_grads", {"step": 5}),
                         ("nan_grads", {"step": 6})):
        model.fit(...)

or from the environment (chaos_smoke campaign stage)::

    PADDLE_TPU_FAULTS="nan_grads@10x3,sigterm@25,slow_step@5:seconds=0.5"

Entry grammar: ``kind[@step][xCOUNT][:k=v;k=v]`` — ``@step`` pins the
fault to a seam step, ``xCOUNT`` arms COUNT firings (default 1), and
``:k=v`` pairs ride as the payload (floats/ints auto-coerced). A
pinned fault with COUNT > 1 is a STORM: it matches the window
[step, step + COUNT), i.e. ``nan_grads@10x3`` poisons steps 10-12 —
exactly the consecutive-bad-step shape that drills rollback.

Seams and their kinds (each seam passes its own step counter):

==================  =====================================================
kind                consulted by
==================  =====================================================
nan_grads           Engine guarded train step (loss *= NaN pre-grad)
slow_step           ServingEngine decode dispatch (host sleep; trips the
                    watchdog), Engine guarded step
dispatch_error      Engine guarded step / ServingEngine dispatch — raises
                    a transient RESOURCE_EXHAUSTED-style error that the
                    retry wrapper absorbs
torn_ckpt           CheckpointManager._write — truncates the state file
                    and suppresses the COMPLETE marker (simulated crash
                    mid-finalize)
sigterm             hapi fit() batch boundary — raises SIGTERM in-process
page_exhaustion     ServingEngine admission — pretends the free list is
                    empty for the matching round
replica_crash       serving_fleet replica worker round — the replica
                    thread dies mid-decode (failover drill)
replica_wedge       serving_fleet replica worker round — the worker stops
                    heartbeating for ``seconds`` (wedge-detection drill)
replica_slow        serving_fleet replica worker round — host sleep per
                    round (tail-latency / hedging drill)
scrape_timeout      FleetRouter health scrape — the scrape raises a
                    transient DEADLINE_EXCEEDED
flaky_transport     ReplicaClient transport op — transient error before
                    (or, with ``after=1``, AFTER) delivery; the retry
                    wrapper + rid idempotency absorb it
router_crash        FleetRouter control round — the router dies mid-step
                    (recovery drill: FleetRouter.recover replays the
                    write-ahead journal and re-adopts the replicas)
journal_torn_write  fleet journal append — the record is written
                    TRUNCATED and JournalCrash raises (process died
                    mid-append); replay drops the torn tail
journal_io_error    fleet journal append — raises JournalError with
                    nothing written (transient disk failure; the
                    router retries lifecycle records, rejects submits)
journal_slow_fsync  fleet journal fsync — host sleep of ``seconds``
                    (slow-disk drill; stalls, never corruption)
replica_exit_at_boot  ProcReplica child boot (serving_fleet/
                    proc_child.py, BEFORE any heavy import) — the
                    subprocess exits nonzero immediately (payload
                    ``exit_code``, default 7). Armed via the child's
                    own ``PADDLE_TPU_PROC_FAULTS`` env; the seam step
                    is the INCARNATION number, so
                    ``replica_exit_at_boot@2x99`` kills every respawn
                    from incarnation 2 on — the crash-loop-breaker
                    drill
replica_slow_boot   ProcReplica child boot — host sleep of ``seconds``
                    before the heavy import (slow-boot-past-the-gate
                    drill; the supervisor's boot timeout kills it).
                    Seam step = incarnation, like exit_at_boot
==================  =====================================================

The journal seams pass the journal's own append (or fsync) sequence
number as the seam step, so ``journal_torn_write@12`` tears exactly
the 12th record this incarnation writes; ``router_crash`` steps are
router control rounds.

Fleet faults target ONE replica via payload (``replica_crash:replica=r1``
or ``inject("replica_crash", replica="r1")``): seams pass their own
identity through ``pull(..., match={"replica": name})`` and a fault
whose payload pins a different identity is skipped without being
consumed. A fault with no ``replica`` payload matches any replica.

The registry is process-global and consult-only-on-armed: ``pull`` on
an empty registry is a tuple check, so production paths pay nothing.
"""
from __future__ import annotations

import contextlib
import os
import signal
import threading
import time

__all__ = ["Fault", "inject", "clear", "armed", "pull", "scenario",
           "load_env", "fired_log", "nan_scale", "maybe_sleep",
           "maybe_raise", "maybe_sigterm", "TransientError"]


class TransientError(RuntimeError):
    """Injected stand-in for a transient runtime/dispatch failure
    (RESOURCE_EXHAUSTED, UNAVAILABLE, ...). The retry wrapper treats it
    — and real errors whose message matches the same grammar — as
    retryable."""


class Fault:
    """One armed fault: fires up to `count` times, optionally pinned to
    a seam step. `payload` rides back to the seam on each firing."""

    __slots__ = ("kind", "step", "count", "payload", "fired")

    def __init__(self, kind, step=None, count=1, **payload):
        self.kind = str(kind)
        self.step = None if step is None else int(step)
        self.count = int(count)
        self.payload = dict(payload)
        self.fired = 0

    @property
    def remaining(self):
        return self.count - self.fired

    def __repr__(self):
        at = "" if self.step is None else f"@{self.step}"
        return (f"Fault({self.kind}{at} x{self.count} "
                f"fired={self.fired} {self.payload})")


_lock = threading.Lock()
_registry: list[Fault] = []
_fired_log: list[tuple[str, int | None]] = []
_env_loaded = False


def inject(kind, step=None, count=1, **payload):
    """Arm one fault. Returns the Fault (inspect `.fired` later)."""
    f = Fault(kind, step=step, count=count, **payload)
    with _lock:
        _registry.append(f)
    return f


def clear():
    """Disarm everything and forget the firing log."""
    with _lock:
        _registry.clear()
        _fired_log.clear()


def armed(kind=None):
    """Any un-exhausted fault (of `kind`, or at all) still armed?"""
    with _lock:
        return any(f.remaining > 0 and (kind is None or f.kind == kind)
                   for f in _registry)


def pull(kind, step=None, match=None):
    """Consume one firing of `kind` matching `step`; returns its payload
    dict, or None when nothing armed matches. A fault armed with
    step=None matches any seam step; a pinned fault matches its storm
    window [step, step + count) — each seam consults a given step once,
    so a pinned count is a run of consecutive steps, not N firings at
    one step. Cheap when the registry is empty (the common case).

    `match` narrows by payload identity (fleet seams): for every key in
    `match`, a fault that PINS that key in its payload must pin the
    same value, or it is skipped WITHOUT being consumed — so
    ``inject("replica_crash", replica="r1")`` fires only for the seam
    pulling with ``match={"replica": "r1"}``, while an unpinned fault
    still matches any puller."""
    if not _registry:          # unlocked fast path: seams in hot loops
        return None
    with _lock:
        for f in _registry:
            if f.kind != kind or f.remaining <= 0:
                continue
            if f.step is not None:
                if step is None:
                    continue
                if not (f.step <= step < f.step + f.count):
                    continue
            if match and any(k in f.payload and f.payload[k] != v
                             for k, v in match.items()):
                continue
            f.fired += 1
            _fired_log.append((kind, step))
            return dict(f.payload)
    return None


def fired_log():
    """(kind, step) tuples in firing order — chaos-test assertions."""
    with _lock:
        return list(_fired_log)


@contextlib.contextmanager
def scenario(*specs):
    """Arm a set of faults for the `with` body, restoring the previous
    registry after. Each spec is a Fault, a kind string, or a
    (kind, kwargs) pair."""
    with _lock:
        saved = list(_registry)
        saved_log = list(_fired_log)
        _registry.clear()
        _fired_log.clear()   # fired_log() inside the scenario reports
        #                      ONLY the scenario's own firings
    for s in specs:
        if isinstance(s, Fault):
            with _lock:
                _registry.append(s)
        elif isinstance(s, str):
            inject(s)
        else:
            kind, kw = s
            inject(kind, **kw)
    try:
        yield
    finally:
        with _lock:
            _registry[:] = saved
            _fired_log[:] = saved_log


def _coerce(v):
    for cast in (int, float):
        try:
            return cast(v)
        except ValueError:
            pass
    return v


def load_env(force=False):
    """Parse PADDLE_TPU_FAULTS (once per process unless force=True).
    Called lazily by the resilience package import; safe to re-call."""
    global _env_loaded
    if _env_loaded and not force:
        return
    _env_loaded = True
    spec = os.environ.get("PADDLE_TPU_FAULTS", "").strip()
    if not spec:
        return
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        payload = {}
        if ":" in entry:
            entry, raw = entry.split(":", 1)
            for pair in raw.split(";"):
                if "=" in pair:
                    k, v = pair.split("=", 1)
                    payload[k.strip()] = _coerce(v.strip())
        count = 1
        if "x" in entry:
            # only a trailing xN with numeric N is a count suffix —
            # kinds themselves may contain 'x' (page_exhaustion)
            head, c = entry.rsplit("x", 1)
            if c.isdigit():
                entry, count = head, int(c)
        step = None
        if "@" in entry:
            entry, s = entry.split("@", 1)
            step = int(s)
        inject(entry.strip(), step=step, count=count, **payload)


# -- seam helpers (one per fault kind, so seams stay one-liners) ----------

def nan_scale(step=None):
    """Guarded-train-step seam: a scalar the step multiplies into the
    loss BEFORE autodiff — NaN poisons the loss and every gradient in
    one shot; 1.0 is the no-fault value. Returned as a host float so it
    rides the step's stable scalar signature (no recompile)."""
    return float("nan") if pull("nan_grads", step) is not None else 1.0


def maybe_sleep(kind="slow_step", step=None, match=None):
    """Host-side stall seam (watchdog/hedging drills). Payload:
    seconds."""
    p = pull(kind, step, match=match)
    if p is not None:
        time.sleep(float(p.get("seconds", 0.05)))
    return p


def maybe_raise(kind="dispatch_error", step=None, match=None):
    """Transient-dispatch-failure seam. Payload: message."""
    p = pull(kind, step, match=match)
    if p is not None:
        raise TransientError(p.get(
            "message", f"RESOURCE_EXHAUSTED: injected {kind} "
                       f"(step={step})"))


def maybe_sigterm(step=None):
    """Preemption seam: deliver SIGTERM to this process at a step
    boundary, exactly like a pod preemption notice."""
    if pull("sigterm", step) is not None:
        signal.raise_signal(signal.SIGTERM)
        return True
    return False
