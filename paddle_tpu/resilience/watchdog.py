"""Dispatch watchdog — detects a wedged device call.

A wedged dispatch (dead tunnel, deadlocked collective, runaway kernel)
looks identical to a slow one from the host: the execute call just
never returns. The watchdog is a daemon thread watching a heartbeat
the caller brackets around each dispatch; when an operation stays in
flight past the timeout it flips `wedged`, bumps `wedge_count`, and
fires the `on_wedge` callback exactly once per in-flight operation
(default: record only — callers decide whether to alert, shed load,
or kill the process; ServingEngine.health() surfaces the state).

It deliberately does NOT try to cancel the dispatch: there is no safe
host-side cancellation of a running XLA execute. Detection + policy
beats a fake kill.
"""
from __future__ import annotations

import contextlib
import threading
import time

__all__ = ["Watchdog"]


class Watchdog:
    def __init__(self, timeout_s=30.0, on_wedge=None, poll_s=None):
        self.timeout_s = float(timeout_s)
        self.on_wedge = on_wedge
        self.poll_s = poll_s if poll_s is not None \
            else max(self.timeout_s / 4.0, 0.005)
        self._lock = threading.Lock()
        self._inflight_op = None
        self._inflight_since = None
        self._flagged = False       # on_wedge fired for current op
        self.wedged = False         # an op is PAST timeout right now
        self.wedge_count = 0        # ops that ever exceeded the timeout
        self.last_wedge_op = None
        self.last_wedge_elapsed = 0.0
        self._stop = threading.Event()
        self._thread = None

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True,
                                            name="paddle-tpu-watchdog")
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.poll_s + 1.0)
            self._thread = None

    # -- heartbeat ---------------------------------------------------------
    def begin(self, op="dispatch"):
        with self._lock:
            self._inflight_op = op
            self._inflight_since = time.monotonic()
            self._flagged = False

    def end(self):
        with self._lock:
            if self._inflight_since is not None and self._flagged:
                # the op eventually returned: it WAS wedged, is no more
                self.last_wedge_elapsed = \
                    time.monotonic() - self._inflight_since
            self._inflight_op = None
            self._inflight_since = None
            self.wedged = False

    @contextlib.contextmanager
    def watch(self, op="dispatch"):
        self.begin(op)
        try:
            yield
        finally:
            self.end()

    # -- monitor -----------------------------------------------------------
    def _loop(self):
        while not self._stop.wait(self.poll_s):
            self.check()

    def check(self):
        """One poll (the thread calls this; tests may call it directly
        for determinism). Returns True when the current op is past the
        timeout."""
        cb = None
        with self._lock:
            since = self._inflight_since
            if since is None:
                return False
            elapsed = time.monotonic() - since
            if elapsed <= self.timeout_s:
                return False
            self.wedged = True
            if not self._flagged:
                self._flagged = True
                self.wedge_count += 1
                self.last_wedge_op = self._inflight_op
                self.last_wedge_elapsed = elapsed
                cb = self.on_wedge
                op = self._inflight_op
        if cb is not None:
            cb(op, elapsed)
        return True

    def health(self):
        with self._lock:
            return {"wedged": self.wedged,
                    "wedge_count": self.wedge_count,
                    "last_wedge_op": self.last_wedge_op,
                    "last_wedge_elapsed_s": round(
                        self.last_wedge_elapsed, 4),
                    "inflight_op": self._inflight_op}
