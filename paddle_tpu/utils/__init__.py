"""paddle.utils parity-lite (ref: python/paddle/utils/*)."""
from __future__ import annotations

import functools
import importlib
import warnings

__all__ = ["try_import", "run_check", "deprecated", "unique_name"]


def try_import(module_name, err_msg=None):
    """ref: paddle.utils.try_import."""
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or
                          f"{module_name} is required but not installed"
                          ) from e


def run_check():
    """ref: paddle.utils.run_check — sanity-check the install + device."""
    import jax
    import jax.numpy as jnp
    devs = jax.devices()
    x = jnp.ones((128, 128))
    y = (x @ x).block_until_ready()
    assert float(y[0, 0]) == 128.0
    print(f"paddle_tpu is installed successfully! "
          f"{len(devs)} x {devs[0].platform} device(s) available.")
    return True


def deprecated(update_to="", since="", reason=""):
    """ref: paddle.utils.deprecated decorator."""
    def wrap(fn):
        @functools.wraps(fn)
        def inner(*a, **kw):
            msg = f"{fn.__name__} is deprecated since {since or '?'}"
            if update_to:
                msg += f", use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*a, **kw)
        return inner
    return wrap


class _UniqueName:
    """ref: paddle.utils.unique_name — generate(), guard(), switch()."""

    def __init__(self):
        self._counters = {}

    def generate(self, key="tmp"):
        n = self._counters.get(key, 0)
        self._counters[key] = n + 1
        return f"{key}_{n}"

    def switch(self, new_generator=None):
        old = dict(self._counters)
        self._counters = new_generator if new_generator is not None else {}
        return old

    def guard(self, new_generator=None):
        import contextlib

        @contextlib.contextmanager
        def cm():
            old = self.switch(new_generator)
            try:
                yield
            finally:
                self._counters = old
        return cm()


unique_name = _UniqueName()


# ---------------------------------------------------------------------------
# dlpack interop (ref: python/paddle/utils/dlpack.py)
# ---------------------------------------------------------------------------
class dlpack:
    """ref: paddle.utils.dlpack — zero-copy tensor exchange with other
    frameworks (torch, numpy, ...) through the DLPack protocol. jax arrays
    already speak __dlpack__; Tensors delegate to their backing array."""

    @staticmethod
    def to_dlpack(x):
        from ..tensor import Tensor
        arr = x._value if isinstance(x, Tensor) else x
        try:
            return arr.__dlpack__()
        except Exception:
            # TPU PJRT buffers don't support external references
            # (PJRT_Buffer_IncreaseExternalReferenceCount unimplemented):
            # export a host copy instead — consumers get the data, not
            # zero-copy device sharing
            import jax
            import numpy as np
            return np.asarray(jax.device_get(arr)).__dlpack__()

    @staticmethod
    def from_dlpack(capsule):
        import jax
        import jax.numpy as jnp
        from ..tensor import Tensor
        if isinstance(capsule, Tensor):
            capsule = capsule._value
        if hasattr(capsule, "__dlpack__"):
            # consumer-style: accept any dlpack-exporting object (torch
            # tensor, numpy array, jax array)
            arr = jnp.from_dlpack(capsule)
        else:
            arr = jax.dlpack.from_dlpack(capsule)
        return Tensor(arr)


to_dlpack = dlpack.to_dlpack
from_dlpack = dlpack.from_dlpack
__all__ += ["dlpack", "to_dlpack", "from_dlpack"]
