"""Normalization layers (ref: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor
from . import functional as F
from .initializer import Constant
from .layer import Layer


_CHANNELS_LAST_BN = {"NCL": "NLC", "NCHW": "NHWC", "NCDHW": "NDHWC"}


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        if not (data_format.startswith("NC") or data_format.endswith("C")):
            raise ValueError(
                f"unsupported BatchNorm data_format {data_format!r}: "
                "expected a channels-first NC* or channels-last N*C spec "
                "(e.g. NCHW | NHWC | NCL | NLC)")
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
            self._parameters["weight"] = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter(
                (num_features,), attr=bias_attr, is_bias=True)
        from ..framework import get_default_dtype, convert_dtype
        dt = convert_dtype(get_default_dtype())
        if dt in (jnp.float16, jnp.bfloat16):
            # ref keeps BN running stats in fp32 under low-precision
            # defaults: momentum-0.9 deltas underflow in 8-bit mantissas
            dt = jnp.float32
        self.register_buffer("_mean",
                             Tensor(jnp.zeros((num_features,), dtype=dt)))
        self.register_buffer("_variance",
                             Tensor(jnp.ones((num_features,), dtype=dt)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def to_channels_last(self):
        """Normalize over the trailing axis (TPU-native channels-last
        stack; see layers_conv.to_channels_last). Stats/affine params
        are per-channel vectors either way — only the reduce axes move,
        so checkpoints are layout-independent. Idempotent."""
        self._data_format = _CHANNELS_LAST_BN.get(self._data_format,
                                                  self._data_format)
        return self

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """ref: nn.SyncBatchNorm (NCCL allreduce of batch stats). On TPU the
    cross-replica reduction happens automatically when the batch axis is
    sharded under pjit: jnp.mean over a sharded axis lowers to a psum over
    the dp mesh axis — so SyncBatchNorm == BatchNorm under pjit.
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for _, sub in layer.named_sublayers(include_self=True):
            for name, child in list(sub._sub_layers.items()):
                if isinstance(child, _BatchNormBase) and not isinstance(child, SyncBatchNorm):
                    sync = SyncBatchNorm(child._num_features, child._momentum,
                                         child._epsilon,
                                         data_format=child._data_format)
                    sync.set_state_dict(child.state_dict())
                    sub._sub_layers[name] = sync
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, (int, np.integer)):
            normalized_shape = (int(normalized_shape),)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
            self._parameters["weight"] = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    def __init__(self, hidden_size, epsilon=1e-6, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.weight = None
            self._parameters["weight"] = None
        else:
            self.weight = self.create_parameter(
                (num_channels,), attr=weight_attr,
                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter(
                (num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
            self._parameters["weight"] = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter(
                (num_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    """ref: nn.SpectralNorm — power-iteration estimate of the spectral norm."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        from ..tensor_ops.random import randn
        self.register_buffer("weight_u", randn((h,)))
        self.register_buffer("weight_v", randn((w,)))

    def forward(self, weight):
        from ..autograd import apply_op
        u0, v0 = self.weight_u, self.weight_v
        dim, eps, iters = self._dim, self._eps, self._power_iters

        def f(w, u, v):
            import jax as _jax
            w_m = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            for _ in range(iters):
                v = _jax.lax.stop_gradient(w_m).T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = _jax.lax.stop_gradient(w_m) @ v
                u = u / (jnp.linalg.norm(u) + eps)
            # u, v are constants of the grad (reference detaches them)
            u = _jax.lax.stop_gradient(u)
            v = _jax.lax.stop_gradient(v)
            sigma = u @ w_m @ v
            return w / sigma, u, v
        out, u_new, v_new = apply_op(f, weight, u0, v0)
        # persist the power-iteration state so sigma sharpens across steps
        # (buffers: picked up by functional_call's mutable collection too)
        u0._value = u_new.detach()._value
        v0._value = v_new.detach()._value
        return out
